"""Dtype-aware fused tile compression (tpusnap/compress.py + the native
shuffle+LZ4 codec) and its probe-driven auto policy.

Covers the acceptance criteria:

- compressed takes restore bit-exact; scrub and fsck validate the
  compressed tiles (bit-rot in one compressed tile is caught and named);
- a pre-compression (uncompressed) snapshot restores bit-exact under the
  new code, and a compression-off take round-trips with no codec fields;
- chaos SIGKILL mid-compressed-take → fsck torn + a salvage-resume
  retake reuses the intact compressed blobs via the dual-hash rule;
- the write-back tiering drain uploads compressed blobs, with the lag
  gauges counting COMPRESSED bytes;
- the auto policy is measured: compress when the codec outruns the
  recorded pipe ceiling, bypass when the pipe outruns the codec (or the
  take is too small to amortize the decision).
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from tpusnap import PytreeState, Snapshot, StateDict, verify_snapshot
from tpusnap import _native, telemetry
from tpusnap import compress as compress_mod
from tpusnap.knobs import (
    override_batching_disabled,
    override_compress,
    override_max_chunk_size_bytes,
    override_memory_budget_bytes,
    override_record_dedup_hashes,
    override_tile_checksum_bytes,
)
from tpusnap.manifest import TensorEntry

needs_native = pytest.mark.skipif(
    not _native.compression_available(),
    reason="native codec unavailable (no toolchain)",
)


def _bf16ish(shape, seed=0):
    """f32 data with bf16 precision (low mantissa bytes zeroed) — the
    mixed-precision-export shape the codec targets; compresses ~2x+."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal(shape).astype(np.float32)
    return (a.view(np.uint32) & np.uint32(0xFFFF0000)).view(np.float32)


def _blob_files(root):
    out = []
    for dirpath, _, files in os.walk(root):
        if ".tpusnap" in dirpath.split(os.sep):
            continue
        for f in files:
            if f != ".snapshot_metadata":
                out.append(os.path.relpath(os.path.join(dirpath, f), root))
    return sorted(out)


def _payload_bytes(root):
    return sum(
        os.path.getsize(os.path.join(root, f)) for f in _blob_files(root)
    )


@pytest.fixture(autouse=True)
def _fresh_policy_state():
    compress_mod._reset_ceilings()
    yield
    compress_mod._reset_ceilings()
    compress_mod.LAST_DECISION = None


# ------------------------------------------------------------ native codec


@needs_native
@pytest.mark.parametrize(
    "dtype,elem",
    [(np.float32, 4), (np.float16, 2), (np.int8, 1), (np.float64, 8)],
)
def test_tile_roundtrip_across_dtypes(dtype, elem):
    rng = np.random.default_rng(11)
    if dtype is np.int8:
        arr = rng.integers(-8, 8, 300_001).astype(dtype)  # low entropy
    else:
        arr = rng.standard_normal(300_001).astype(dtype)  # odd length tail
    buf = arr.tobytes()
    tile = 1 << 16  # many tiles, short last tile
    out, sizes, crcs, xxhs = _native.compress_tiles(buf, tile, elem, True)
    assert sum(sizes) == out.nbytes
    n_tiles = (len(buf) + tile - 1) // tile
    assert len(sizes) == len(crcs) == len(xxhs) == n_tiles
    # The recorded hashes are over the STORED bytes of each tile.
    off = 0
    for i, s in enumerate(sizes):
        assert _native.crc32c(bytes(out[off : off + s])) == crcs[i]
        off += s
    dec = bytearray(len(buf))
    _native.decompress_tiles(out, sizes, tile, len(buf), elem, dec)
    assert bytes(dec) == buf


@needs_native
def test_incompressible_tiles_stored_raw():
    """Random bytes do not shrink: every tile stores raw (comp size ==
    raw tile size — the decoder's unambiguous marker) and the total
    never exceeds the input."""
    buf = np.random.default_rng(1).integers(0, 255, 1 << 18, dtype=np.uint8)
    buf = buf.tobytes()
    tile = 1 << 16
    out, sizes, _, _ = _native.compress_tiles(buf, tile, 1, False)
    assert out.nbytes == len(buf)
    assert all(s == tile for s in sizes)
    dec = bytearray(len(buf))
    _native.decompress_tiles(out, sizes, tile, len(buf), 1, dec)
    assert bytes(dec) == buf


@needs_native
def test_codec_is_deterministic():
    """Equal input bytes always yield equal stored bytes — the property
    incremental dedup and salvage-resume rest on."""
    buf = _bf16ish((512, 128)).tobytes()
    a, sa, ca, xa = _native.compress_tiles(buf, 1 << 16, 4, True, nthreads=4)
    b, sb, cb, xb = _native.compress_tiles(buf, 1 << 16, 4, True, nthreads=1)
    assert bytes(a) == bytes(b) and sa == sb and ca == cb and xa == xb


@needs_native
def test_python_fallback_decode_matches_native():
    """The pure-Python LZ4+unshuffle decoder (TPUSNAP_DISABLE_NATIVE
    restores) decodes native-compressed tiles bit-exactly."""
    arr = _bf16ish((300, 77), seed=5)
    buf = arr.tobytes()
    tile = 1 << 14
    out, sizes, _, _ = _native.compress_tiles(buf, tile, 4, False)
    dec = bytearray(len(buf))
    _native._py_decompress_tiles(
        memoryview(bytes(out)), sizes, tile, len(buf), 4, memoryview(dec)
    )
    assert bytes(dec) == buf


@needs_native
def test_malformed_compressed_input_raises_cleanly():
    buf = _bf16ish((256, 64)).tobytes()
    out, sizes, _, _ = _native.compress_tiles(buf, len(buf), 4, False)
    assert out.nbytes < len(buf)
    # Truncated stream, garbage stream, wrong sizes: CompressionError,
    # never OOB writes or hangs — in BOTH decoders.
    for decoder in ("native", "python"):

        def dec(src, szs):
            o = bytearray(len(buf))
            if decoder == "native":
                _native.decompress_tiles(src, szs, len(buf), len(buf), 4, o)
            else:
                _native._py_decompress_tiles(
                    memoryview(bytes(src)), szs, len(buf), len(buf), 4,
                    memoryview(o),
                )

        with pytest.raises(_native.CompressionError):
            dec(out[: out.nbytes // 2], [out.nbytes // 2])
        garbage = np.frombuffer(os.urandom(out.nbytes), dtype=np.uint8)
        with pytest.raises(_native.CompressionError):
            dec(garbage, sizes)
        with pytest.raises(_native.CompressionError):
            dec(out, [out.nbytes + 7])


# ------------------------------------------------------------- policy units


def test_codec_for_dtype_mapping():
    assert compress_mod.codec_for_dtype("float32") == "shuf4+lz4"
    assert compress_mod.codec_for_dtype("bfloat16") == "shuf2+lz4"
    assert compress_mod.codec_for_dtype("float16") == "shuf2+lz4"
    assert compress_mod.codec_for_dtype("float64") == "shuf8+lz4"
    assert compress_mod.codec_for_dtype("int8") == "lz4"
    assert compress_mod.codec_for_dtype("no_such_dtype") is None
    assert compress_mod.codec_elem("shuf4+lz4") == 4
    assert compress_mod.codec_elem("lz4") == 1
    with pytest.raises(ValueError, match="newer"):
        compress_mod.codec_elem("zstd19")  # future codec: loud refusal


def _mk_reqs(nbytes=1 << 20, dtype=np.float32):
    """One real ArrayBufferStager-backed write request, policy-eligible."""
    from tpusnap.io_preparers.array import ArrayBufferStager
    from tpusnap.io_types import WriteReq
    from tpusnap.serialization import dtype_to_string

    arr = np.zeros(nbytes // np.dtype(dtype).itemsize, dtype=dtype)
    entry = TensorEntry(
        location="0/w",
        serializer="buffer_protocol",
        dtype=dtype_to_string(arr.dtype),
        shape=list(arr.shape),
        replicated=False,
    )
    stager = ArrayBufferStager(arr, is_async_snapshot=False, entry=entry)
    return [WriteReq(path="0/w", buffer_stager=stager)], stager


@needs_native
def test_auto_policy_decision_matrix(monkeypatch):
    monkeypatch.setattr(compress_mod, "codec_throughput_gbps", lambda: 2.0)
    monkeypatch.setattr(compress_mod, "AUTO_MIN_TAKE_BYTES", 1 << 18)

    # Pipe faster than codec (local NVMe): bypass.
    reqs, st = _mk_reqs()
    compress_mod.note_pipe_ceiling("X", 10.0)
    monkeypatch.setattr(compress_mod, "pipe_ceiling", lambda label: 10.0)
    with override_compress(mode="auto", min_blob_bytes=65536):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert (d.compress, d.reason) == (False, "pipe_outruns_codec")
    assert st.compress_codec is None

    # Pipe slower than codec (cloud): compress.
    monkeypatch.setattr(compress_mod, "pipe_ceiling", lambda label: 0.2)
    reqs, st = _mk_reqs()
    with override_compress(mode="auto", min_blob_bytes=65536):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert (d.compress, d.reason) == (True, "codec_outruns_pipe")
    assert st.compress_codec == "shuf4+lz4"
    assert d.pipe_gbps == 0.2 and d.codec_gbps == 2.0

    # At the margin (codec < pipe * 1.3): bypass — parity gains nothing.
    monkeypatch.setattr(compress_mod, "pipe_ceiling", lambda label: 1.8)
    reqs, st = _mk_reqs()
    with override_compress(mode="auto", min_blob_bytes=65536):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert not d.compress

    # Below the auto floor: bypass without consulting any ceiling.
    reqs, st = _mk_reqs(nbytes=1 << 17)
    with override_compress(mode="auto", min_blob_bytes=65536):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert (d.compress, d.reason) == (False, "below_auto_floor")


@needs_native
def test_forced_modes_and_eligibility(monkeypatch):
    monkeypatch.setattr(compress_mod, "codec_throughput_gbps", lambda: 2.0)
    # off: never compresses.
    reqs, st = _mk_reqs()
    with override_compress(mode="off"):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert (d.compress, d.reason) == (False, "mode_off")
    # on: compresses without a ceiling.
    reqs, st = _mk_reqs()
    with override_compress(mode="on", min_blob_bytes=65536):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert (d.compress, d.reason) == (True, "mode_forced")
    # Below the per-blob floor: not eligible even when forced.
    reqs, st = _mk_reqs(nbytes=1 << 17)
    with override_compress(mode="on", min_blob_bytes=1 << 20):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert (d.compress, d.reason) == (False, "no_eligible_blobs")
    # compressible=False (sharded shards): constructed out.
    reqs, st = _mk_reqs()
    st.compressible = False
    with override_compress(mode="on", min_blob_bytes=65536):
        d = compress_mod.apply_take_policy(reqs, None, None)
    assert d.reason == "no_eligible_blobs"


@needs_native
def test_policy_mini_probe_measures_and_cleans_up(tmp_path, monkeypatch):
    """auto with no recorded ceiling: the one-shot mini-probe measures
    through the take's own plugin stack, caches the ceiling, and leaves
    no probe files behind."""
    import asyncio

    from tpusnap.storage_plugin import url_to_storage_plugin_in_event_loop

    monkeypatch.setattr(compress_mod, "AUTO_MIN_TAKE_BYTES", 1 << 18)
    loop = asyncio.new_event_loop()
    storage = url_to_storage_plugin_in_event_loop(str(tmp_path), loop)
    try:
        # Device-scoped registry key (two same-class backends on
        # different mounts must not share a ceiling sample).
        label = compress_mod.pipe_ceiling_key(storage)
        assert "@" in label
        compress_mod._reset_ceilings()
        assert compress_mod.pipe_ceiling(label) is None
        reqs, _ = _mk_reqs()
        with override_compress(mode="auto", min_blob_bytes=65536):
            d = compress_mod.apply_take_policy(reqs, storage, loop)
        assert d.reason in ("codec_outruns_pipe", "pipe_outruns_codec")
        assert d.pipe_gbps and d.pipe_gbps > 0
        assert compress_mod.pipe_ceiling(label) == pytest.approx(
            d.pipe_gbps, rel=1e-3
        )
        assert not os.path.exists(str(tmp_path / ".tpusnap" / "probe")) or (
            os.listdir(str(tmp_path / ".tpusnap" / "probe")) == []
        )
    finally:
        storage.sync_close(loop)
        loop.close()


def test_unknown_mode_warns_and_falls_back(monkeypatch):
    from tpusnap.knobs import get_compress_mode

    monkeypatch.setenv("TPUSNAP_COMPRESS", "zstd-max")
    assert get_compress_mode() == "auto"


# ----------------------------------------------------------- end to end


@needs_native
def test_take_scrub_restore_roundtrip(tmp_path):
    """Forced compression: the stored payload shrinks, the manifest
    carries the codec fields, scrub verifies the compressed tiles, and
    the restore is bit-exact (f32 shuffle codec + int8 plain LZ4)."""
    a = _bf16ish((2048, 256))
    b = np.random.default_rng(2).integers(-4, 4, (512, 512)).astype(np.int8)
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True):
        snap = Snapshot.take(path, {"app": StateDict(w=a.copy(), q=b.copy())})
    d = compress_mod.LAST_DECISION
    assert d is not None and d.compress and d.mode == "on"
    assert _payload_bytes(path) < (a.nbytes + b.nbytes) * 0.8
    md = Snapshot(path).metadata
    entry = md.manifest["0/app/w"]
    assert entry.codec == "shuf4+lz4"
    assert entry.uncompressed_nbytes == a.nbytes
    assert sum(entry.comp_tile_sizes) == os.path.getsize(
        os.path.join(path, "0/app/w")
    )
    assert md.manifest["0/app/q"].codec == "lz4"
    rep = snap.verify()
    assert rep.clean and rep.corrupt == 0 and rep.ok > 0
    tgt = {"app": StateDict(w=np.zeros_like(a), q=np.zeros_like(b))}
    Snapshot(path).restore(tgt)
    assert np.array_equal(tgt["app"]["w"], a)
    assert np.array_equal(tgt["app"]["q"], b)


@needs_native
def test_tiled_budget_restore_and_read_object(tmp_path):
    """Small checksum tiles + a small memory budget: the restore reads
    compressed tile groups under the budget, and read_object random
    access works at tile grain."""
    a = _bf16ish((4096, 64), seed=9)  # 1 MiB, 16 tiles of 64 KiB raw
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_tile_checksum_bytes(1 << 16):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    entry = Snapshot(path).metadata.manifest["0/app/w"]
    assert entry.codec and len(entry.comp_tile_sizes) == 16
    assert len(entry.tile_checksums) == 16
    got = Snapshot(path).read_object(
        "0/app/w", memory_budget_bytes=1 << 17
    )
    assert np.array_equal(got, a)
    tgt = {"app": StateDict(w=np.zeros_like(a))}
    with override_memory_budget_bytes(1 << 17):
        Snapshot(path).restore(tgt)
    assert np.array_equal(tgt["app"]["w"], a)


@needs_native
def test_truncated_comp_tile_sizes_refused(tmp_path):
    """A codec entry whose comp_tile_sizes under-covers the payload
    (buggy external rewriter) must REFUSE to restore: every per-group
    checksum of a truncated list would verify, leaving the destination
    tail silently unwritten."""
    from concurrent.futures import Future

    from tpusnap.io_preparers.array import ArrayIOPreparer

    a = _bf16ish((4096, 64), seed=5)
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_tile_checksum_bytes(1 << 16):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    entry = Snapshot(path).metadata.manifest["0/app/w"]
    assert len(entry.comp_tile_sizes) == 16
    entry.comp_tile_sizes = entry.comp_tile_sizes[:-2]  # rewriter bug
    with pytest.raises(IOError, match="spans 16"):
        ArrayIOPreparer._prepare_compressed_read(entry, None, None, Future())


@needs_native
def test_bitrot_in_compressed_tile_caught_and_named(tmp_path):
    """Flip one byte inside one compressed tile: scrub names the tile,
    restore refuses with a checksum error — bit-rot never decodes to
    silently wrong values."""
    a = _bf16ish((4096, 64), seed=3)
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_tile_checksum_bytes(1 << 16):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    blob = os.path.join(path, "0/app/w")
    with open(blob, "r+b") as f:
        f.seek(os.path.getsize(blob) // 2)
        c = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([c[0] ^ 0xFF]))
    rep = verify_snapshot(path)
    assert not rep.clean and rep.corrupt == 1
    assert "comp tile" in rep.failures[0].detail
    with pytest.raises(Exception, match="hecksum|orrupt"):
        Snapshot(path).restore({"app": StateDict(w=np.zeros_like(a))})


@needs_native
def test_compression_off_snapshot_roundtrips_without_codec_fields(tmp_path):
    """TPUSNAP_COMPRESS=off writes the pre-compression format exactly:
    no codec fields anywhere (the cross-version guarantee — a pre-14
    snapshot IS a compression-off snapshot), and it restores bit-exact
    under the codec-aware reader."""
    import json

    a = _bf16ish((1024, 256), seed=7)
    path = str(tmp_path / "snap")
    with override_compress(mode="off"), override_batching_disabled(True):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    assert compress_mod.LAST_DECISION.reason == "mode_off"
    raw = open(os.path.join(path, ".snapshot_metadata"), "rb").read()
    assert b'"codec"' not in raw and b"comp_tile_sizes" not in raw.replace(
        b" ", b""
    )
    md = json.loads(raw)
    entry = md["manifest"]["0/app/w"]
    assert "codec" not in entry and "uncompressed_nbytes" not in entry
    assert _payload_bytes(path) == a.nbytes
    tgt = {"app": StateDict(w=np.zeros_like(a))}
    Snapshot(path).restore(tgt)
    assert np.array_equal(tgt["app"]["w"], a)
    assert verify_snapshot(path).clean


@needs_native
def test_chunked_array_compresses_per_chunk(tmp_path):
    """An array above the max-chunk bound: each chunk blob compresses
    independently and the chunked restore decodes into its rows."""
    a = _bf16ish((4096, 64), seed=4)  # 1 MiB
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_max_chunk_size_bytes(
        1 << 18
    ):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    from tpusnap.manifest import ChunkedTensorEntry

    entry = Snapshot(path).metadata.manifest["0/app/w"]
    assert isinstance(entry, ChunkedTensorEntry) and len(entry.chunks) == 4
    assert all(c.tensor.codec == "shuf4+lz4" for c in entry.chunks)
    assert _payload_bytes(path) < a.nbytes * 0.8
    assert verify_snapshot(path).clean
    tgt = {"app": StateDict(w=np.zeros_like(a))}
    Snapshot(path).restore(tgt)
    assert np.array_equal(tgt["app"]["w"], a)


@needs_native
def test_async_take_compressed_skips_cow_and_clone(tmp_path):
    """Async takes: the compressed buffer is fresh memory — no defensive
    clone, no COW write-time verify — so mutating after wait_staged()
    commits the pre-mutation bytes in the DEFAULT staging mode."""
    a = _bf16ish((2048, 256), seed=6)
    orig = a.copy()
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True):
        pending = Snapshot.async_take(path, {"app": StateDict(w=a)})
        assert pending.wait_staged(timeout=60)
        a[:] = -1.0
        pending.wait()
    summary = telemetry.LAST_TAKE_SUMMARY
    assert summary["stages"].get("cow_verify") is None
    tgt = {"app": StateDict(w=np.zeros_like(a))}
    Snapshot(path).restore(tgt)
    assert np.array_equal(tgt["app"]["w"], orig)


@needs_native
def test_incremental_dedup_over_compressed_bytes(tmp_path):
    """Unchanged arrays dedup against a compressed base at whole-blob
    grain (deterministic codec: equal input ⇒ equal stored hashes); a
    RAW base conservatively rewrites (codec is part of the identity)."""
    a = _bf16ish((1024, 256), seed=8)
    b = _bf16ish((1024, 256), seed=9)
    base, inc, inc2 = (
        str(tmp_path / "s0"), str(tmp_path / "s1"), str(tmp_path / "s2"),
    )
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_record_dedup_hashes(True):
        Snapshot.take(base, {"app": StateDict(x=a.copy(), y=b.copy())})
        # Unchanged state: both blobs skip.
        Snapshot.take(
            inc, {"app": StateDict(x=a.copy(), y=b.copy())},
            incremental_from=base,
        )
        assert _blob_files(inc) == []
        # One changed leaf: exactly one compressed blob rewrites.
        b2 = b.copy()
        b2[0, 0] += 1.0
        Snapshot.take(
            inc2, {"app": StateDict(x=a.copy(), y=b2)}, incremental_from=inc
        )
    assert _blob_files(inc2) == ["0/app/y"]
    md = Snapshot(inc2).metadata
    assert md.manifest["0/app/x"].location.startswith("../")
    tgt = {"app": StateDict(x=np.zeros_like(a), y=np.zeros_like(b))}
    Snapshot(inc2).restore(tgt)
    assert np.array_equal(tgt["app"]["x"], a)
    assert np.array_equal(tgt["app"]["y"], b2)

    # Raw base → compressed increment: no skip (identity mismatch).
    raw_base, c_inc = str(tmp_path / "r0"), str(tmp_path / "r1")
    with override_batching_disabled(True), override_record_dedup_hashes(True):
        with override_compress(mode="off"):
            Snapshot.take(raw_base, {"app": StateDict(x=a.copy())})
        with override_compress(mode="on", min_blob_bytes=65536):
            Snapshot.take(
                c_inc, {"app": StateDict(x=a.copy())},
                incremental_from=raw_base,
            )
    assert _blob_files(c_inc) == ["0/app/x"]
    assert verify_snapshot(c_inc).clean


@needs_native
def test_unchanged_compressed_blob_skips_the_codec_pass(tmp_path):
    """The raw-hash fast path: an unchanged blob deduping against a
    compressed base costs a hash pass, NOT a codec pass (a frozen model
    must not re-compress per micro-commit to write zero bytes). The
    base records uncompressed_dedup_hash; the increment's skip adopts
    the base's stored representation wholesale and still restores
    bit-exact."""
    a = _bf16ish((1024, 256), seed=12)
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_record_dedup_hashes(True):
        Snapshot.take(base, {"app": StateDict(x=a.copy())})
        assert Snapshot(base).metadata.manifest[
            "0/app/x"
        ].uncompressed_dedup_hash
        bytes_in_before = telemetry.counter_value("compress.bytes_in")
        skips_before = telemetry.counter_value("compress.raw_dedup_skips")
        Snapshot.take(
            inc, {"app": StateDict(x=a.copy())}, incremental_from=base
        )
    assert _blob_files(inc) == []
    assert telemetry.counter_value("compress.bytes_in") == bytes_in_before
    assert telemetry.counter_value("compress.raw_dedup_skips") == (
        skips_before + 1
    )
    e = Snapshot(inc).metadata.manifest["0/app/x"]
    assert e.codec and e.comp_tile_sizes and e.uncompressed_dedup_hash
    tgt = {"app": StateDict(x=np.zeros_like(a))}
    Snapshot(inc).restore(tgt)
    assert np.array_equal(tgt["app"]["x"], a)


@needs_native
def test_materialize_carries_compressed_blobs(tmp_path):
    """materialize copies a compressed base blob verbatim: the codec
    fields travel with the entry and the copied range verifies against
    the stored-bytes checksums."""
    a = _bf16ish((1024, 256), seed=12)
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_record_dedup_hashes(True):
        Snapshot.take(base, {"app": StateDict(x=a.copy())})
        Snapshot.take(inc, {"app": StateDict(x=a.copy())},
                      incremental_from=base)
    assert _blob_files(inc) == []
    stats = Snapshot(inc).materialize()
    assert stats["blobs_copied"] == 1
    import shutil

    shutil.rmtree(base)
    assert verify_snapshot(inc).clean
    tgt = {"app": StateDict(x=np.zeros_like(a))}
    Snapshot(inc).restore(tgt)
    assert np.array_equal(tgt["app"]["x"], a)
    assert Snapshot(inc).metadata.manifest["0/app/x"].codec == "shuf4+lz4"


# ------------------------------------------------------ crash + salvage

_COMPRESSED_CRASH_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["TPUSNAP_COMPRESS"] = "on"
os.environ["TPUSNAP_COMPRESS_MIN_BLOB_BYTES"] = "65536"
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

path, crash_at = sys.argv[1], int(sys.argv[2])
rng = np.random.default_rng(0)
state = {}
for i in range(10):
    a = rng.standard_normal((256, 256)).astype(np.float32)
    state[f"w{i}"] = (a.view(np.uint32) & np.uint32(0xFFFF0000)).view(np.float32)
Snapshot.take(
    "chaos+fs://" + path,
    {"app": StateDict(**state)},
    storage_options={"fault_plan": {"seed": 0, "crash_after_op": ("write", crash_at)}},
)
print("UNEXPECTED_COMPLETION", flush=True)
"""


@pytest.mark.chaos
@needs_native
def test_sigkill_mid_compressed_take_salvage_reuses_blobs(tmp_path):
    """SIGKILL after N compressed blob writes → fsck torn; a retake with
    the same state re-compresses deterministically and the dual-hash
    rule licenses reuse of the intact COMPRESSED blobs; the final
    snapshot restores bit-exact and scrubs clean."""
    from tpusnap.lifecycle import fsck_snapshot

    path = str(tmp_path / "snap")
    proc = subprocess.run(
        [sys.executable, "-c", _COMPRESSED_CRASH_CHILD, path, "6"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=150,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == -signal.SIGKILL, proc.stdout[-2000:]

    report = fsck_snapshot(path)
    assert report.state == "torn", report.summary()
    assert report.salvage_bytes_present > 0

    rng = np.random.default_rng(0)
    expected = {}
    for i in range(10):
        a = rng.standard_normal((256, 256)).astype(np.float32)
        expected[f"w{i}"] = (
            a.view(np.uint32) & np.uint32(0xFFFF0000)
        ).view(np.float32)

    before = telemetry.counter_value("salvage.bytes_salvaged")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True):
        Snapshot.take(path, {"app": StateDict(**expected)})
    salvaged = telemetry.counter_value("salvage.bytes_salvaged") - before
    assert salvaged >= 0.5 * report.salvage_bytes_present, (
        salvaged, report.salvage_bytes_present,
    )
    assert fsck_snapshot(path).state == "committed"
    assert verify_snapshot(path).clean
    raw = sum(v.nbytes for v in expected.values())
    assert _payload_bytes(path) < raw * 0.8  # the committed blobs ARE compressed
    tgt = {"app": StateDict(**{k: np.zeros_like(v) for k, v in expected.items()})}
    Snapshot(path).restore(tgt)
    for k, v in expected.items():
        assert np.array_equal(tgt["app"][k], v), k


# ------------------------------------------------------------- tiering


@pytest.mark.tiering
@needs_native
def test_tiering_drain_counts_compressed_bytes(tmp_path):
    """A tiered compressed take: the lag gauge counts COMPRESSED bytes
    (the upload backlog the wire actually sees), the drain uploads them
    with journal evidence over the stored bytes, and the remote tier
    restores bit-exact."""
    from tpusnap.tiering import (
        drain_snapshot,
        parse_tier_url,
        tier_state_of_dir,
    )

    local = tmp_path / "local"
    remote = tmp_path / "remote"
    local.mkdir()
    remote.mkdir()
    url = f"tier+local={local}+remote=fs://{remote}/snap"
    a = _bf16ish((2048, 256), seed=13)
    from tpusnap.knobs import override_tier_drain

    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True), override_tier_drain(False):
        Snapshot.take(url, {"app": StateDict(w=a.copy())})
    local_dir = parse_tier_url(url).local_dir
    stored = _payload_bytes(local_dir)
    assert stored < a.nbytes * 0.8  # landed compressed locally
    st = tier_state_of_dir(local_dir)
    assert st["durability"] == "local-committed"
    assert 0 < st["lag_bytes"] <= stored + 4096  # compressed backlog
    assert st["lag_bytes"] < a.nbytes  # NOT the raw size

    report = drain_snapshot(url)
    assert report.state == "durable"
    assert tier_state_of_dir(local_dir)["lag_bytes"] == 0
    tgt = {"app": StateDict(w=np.zeros_like(a))}
    Snapshot(str(remote / "snap")).restore(tgt)
    assert np.array_equal(tgt["app"]["w"], a)
    assert verify_snapshot(str(remote / "snap")).clean


# -------------------------------------------------------- observability


@needs_native
def test_decision_and_ratio_ride_summary_history_and_prom(tmp_path):
    """The resolved policy decision + codec counters land in the take
    summary, flow into the history event (flat gateable scalars) and
    the Prometheus textfile export."""
    from tpusnap.history import event_from_summary
    from tpusnap.metrics_export import (
        PrometheusTextfileSink,
        parse_prometheus_textfile,
    )

    a = _bf16ish((2048, 256), seed=14)
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    summary = telemetry.LAST_TAKE_SUMMARY
    comp = summary.get("compress")
    assert comp and comp["decision"] == "compress"
    assert comp["codec_gbps"] > 0
    counters = summary["counters"]
    assert counters["compress.bytes_in"] == a.nbytes
    assert 0 < counters["compress.bytes_out"] < a.nbytes
    assert summary["stages"]["compress"]["count"] == 1

    ev = event_from_summary("take", summary)
    assert ev["compress_decision"] == "compress"
    assert ev["compress_ratio"] > 1.2
    assert ev["compress_codec_gbps"] > 0
    assert ev["compress_bytes_out"] == counters["compress.bytes_out"]

    sink = PrometheusTextfileSink(directory=str(tmp_path / "prom"))
    sink.on_take_summary(summary)
    from tpusnap.knobs import get_job_id

    prom_file = os.path.join(
        str(tmp_path / "prom"),
        f"tpusnap_{get_job_id()}_rank{summary['rank']}.prom",
    )
    families = parse_prometheus_textfile(open(prom_file).read())
    assert families["tpusnap_compress_bytes_in_total"]["samples"]
    assert families["tpusnap_compress_bytes_out_total"]["samples"]

    # The cross-rank rollup folds the codec counters.
    rollup = (Snapshot(path).metadata.extras or {}).get("telemetry", {})
    assert rollup.get("counters", {}).get("compress.bytes_in") == a.nbytes


def test_analyze_attributes_compress_as_own_resource():
    from tpusnap.analyze import ADVICE, WORK_PRIORITY, classify_span

    assert classify_span("compress") == "compress"
    assert "compress" in WORK_PRIORITY
    assert "TPUSNAP_COMPRESS" in ADVICE["compress"]
    # The write-bound advice recommends the policy flip the other way.
    assert "TPUSNAP_COMPRESS" in ADVICE["storage_write"]


@needs_native
def test_restore_under_disabled_native_decodes_compressed(tmp_path):
    """A compressed snapshot restores bit-exact with the native engine
    disabled (pure-Python LZ4 decode + unshuffle) — slow, but never a
    bricked checkpoint on a host without a toolchain."""
    a = _bf16ish((512, 64), seed=15)  # small: the Python decoder is slow
    path = str(tmp_path / "snap")
    with override_compress(
        mode="on", min_blob_bytes=65536
    ), override_batching_disabled(True):
        Snapshot.take(path, {"app": StateDict(w=a.copy())})
    assert Snapshot(path).metadata.manifest["0/app/w"].codec
    child = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TPUSNAP_DISABLE_NATIVE"] = "1"
import numpy as np
from tpusnap import Snapshot, StateDict
path = sys.argv[1]
a = np.zeros((512, 64), dtype=np.float32)
tgt = {"app": StateDict(w=a)}
Snapshot(path).restore(tgt)
np.save(sys.argv[2], tgt["app"]["w"])
"""
    out_npy = str(tmp_path / "restored.npy")
    proc = subprocess.run(
        [sys.executable, "-c", child, path, out_npy],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert np.array_equal(np.load(out_npy), a)
