"""Cross-run history tests: recording from real takes/restores (cold
tagging, aborted takes excluded), crash-tolerant parsing of a torn
final line, the size bound, the trailing-median regression check
(including the cold-run-only outlier acceptance case), and the
``tpusnap history`` CLI exit codes.
"""

import json
import os

import numpy as np
import pytest

from tpusnap import (
    FaultPlan,
    PytreeState,
    Snapshot,
    check_regression,
    load_history,
    record_event,
)
from tpusnap import history as hist
from tpusnap.__main__ import main
from tpusnap.knobs import (
    override_history_enabled,
    override_history_max_bytes,
    override_telemetry_dir,
)


def _state(total_bytes=1 << 20, n=2):
    per = max(total_bytes // n // 4, 16)
    return {f"w{i}": np.arange(per, dtype=np.float32) + i for i in range(n)}


@pytest.fixture
def history_env(tmp_path):
    """Isolated telemetry dir + fresh per-process cold-tag state."""
    with override_telemetry_dir(str(tmp_path / "tele")):
        hist._reset_process_state()
        yield hist.history_path()
    hist._reset_process_state()


def _synth(i, gbps, kind="take", world=1, **kw):
    return {
        "v": 1,
        "ts": 1e9 + i,
        "kind": kind,
        "rank": 0,
        "world_size": world,
        "wall_s": 2.0,
        "bytes": int(gbps * 2e9),
        "throughput_gbps": gbps,
        **kw,
    }


# -------------------------------------------------------------- recording


def test_take_and_restore_record_history(tmp_path, history_env):
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": PytreeState(_state())})
    target = {k: np.zeros_like(v) for k, v in _state().items()}
    Snapshot(path).restore({"m": PytreeState(target)})
    Snapshot.take(str(tmp_path / "snap2"), {"m": PytreeState(_state())})
    events = load_history()
    kinds = [e["kind"] for e in events]
    assert kinds == ["take", "restore", "take"]
    take1, restore, take2 = events
    assert take1["bytes"] > 0 and take1["throughput_gbps"] > 0
    assert take1["wall_s"] > 0 and take1["world_size"] == 1
    assert take1["take_id"] and take1["path"] == path
    assert "stage" in take1["phases_s"]
    assert restore["bytes"] > 0 and "restore.read" in restore["phases_s"]
    # First event of each KIND in the process is cold-tagged; later ones
    # are not (the regression check's warmup awareness rides this).
    assert take1.get("cold") is True
    assert restore.get("cold") is True
    assert "cold" not in take2


def test_incomplete_summary_not_recorded(history_env):
    assert (
        hist.record_summary(
            "take", {"rank": 0, "take_wall_s": 1.0, "counters": {}}
        )
        is None
    )
    assert not os.path.exists(history_env)


@pytest.mark.chaos
def test_failed_take_not_recorded(tmp_path, history_env):
    with pytest.raises(Exception):
        Snapshot.take(
            "chaos+fs://" + str(tmp_path / "snap"),
            {"m": PytreeState(_state())},
            storage_options={
                "retry": False,
                "fault_plan": FaultPlan(seed=1, transient_per_op=100),
            },
        )
    assert [e["kind"] for e in load_history()] == []


def test_history_disabled_knob(tmp_path, history_env):
    with override_history_enabled(False):
        Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    assert not os.path.exists(history_env)
    assert load_history() == []


# ------------------------------------------------------- crash tolerance


def test_torn_final_line_survives(history_env):
    for i in range(3):
        record_event(_synth(i, 1.0))
    # Crash mid-append: a torn final line with no newline.
    with open(history_env, "ab") as f:
        f.write(b'{"v":1,"kind":"take","thro')
    events = load_history()
    assert len(events) == 3  # torn tail dropped, earlier lines intact
    # The next append isolates the torn fragment on its own line
    # instead of concatenating onto it.
    record_event(_synth(3, 1.0))
    events = load_history()
    assert len(events) == 4
    assert events[-1]["ts"] == 1e9 + 3


def test_size_bound_compaction(history_env):
    with override_history_max_bytes(1):  # floor: 64 KiB
        pad = "x" * 150  # ~200B/line -> bound crossed well within 600
        for i in range(600):
            record_event(_synth(i, 1.0, note=pad))
        assert os.path.getsize(history_env) <= 64 * 1024
        events = load_history()
        assert events, "compaction must keep the newest lines"
        assert events[-1]["ts"] == 1e9 + 599  # newest survives
        assert events[0]["ts"] > 1e9  # oldest did not
        for e in events:
            assert e["note"] == pad  # every surviving line parses whole


# ------------------------------------------------------ regression check


def test_check_regression_flags_throughput_drop():
    events = [_synth(i, 1.0 + 0.01 * i) for i in range(10)]
    events.append(_synth(10, 0.5))
    r = check_regression(events, threshold=0.25)
    assert r.ok and r.regressed
    assert "below" in r.reason
    assert r.baseline_median == pytest.approx(1.04, abs=0.01)


def test_check_regression_ok_within_threshold():
    events = [_synth(i, 1.0) for i in range(10)]
    events.append(_synth(10, 0.9))
    r = check_regression(events, threshold=0.25)
    assert r.ok and not r.regressed


def test_check_cold_latest_passes():
    """Acceptance: a cold-run-only outlier (warmup) must NOT flag."""
    events = [_synth(i, 1.0) for i in range(10)]
    events.append(_synth(10, 0.2, cold=True))
    r = check_regression(events, threshold=0.25)
    assert r.ok and not r.regressed
    assert "cold" in r.reason


def test_check_all_cold_fleet_grades_cold_vs_cold():
    """One-take-per-process fleets tag EVERY event cold; the gate must
    grade cold runs against the trailing cold baseline like-for-like
    instead of being structurally green."""
    events = [_synth(i, 1.0, cold=True) for i in range(8)]
    events.append(_synth(8, 0.4, cold=True))
    r = check_regression(events, threshold=0.25)
    assert r.ok and r.regressed
    assert "cold-vs-cold" in r.reason
    # Healthy all-cold trend still passes.
    r = check_regression(events[:-1], threshold=0.25)
    assert r.ok and not r.regressed


def test_check_cold_events_excluded_from_baseline():
    # A cold crawl at the head must not drag the median down and mask a
    # real regression.
    events = [_synth(0, 0.1, cold=True)]
    events += [_synth(i, 1.0) for i in range(1, 6)]
    events.append(_synth(6, 0.6))
    r = check_regression(events, threshold=0.25)
    assert r.regressed
    assert r.baseline_median == pytest.approx(1.0)


def test_check_insufficient_history():
    r = check_regression([_synth(0, 1.0), _synth(1, 0.1)], min_baseline=3)
    assert not r.ok and not r.regressed
    r = check_regression([], min_baseline=3)
    assert not r.ok and not r.regressed


def test_check_world_size_mismatch_excluded():
    events = [_synth(i, 4.0, world=8) for i in range(10)]
    events += [_synth(10 + i, 1.0) for i in range(4)]
    # Latest is world=1: the world=8 runs are incommensurable and must
    # not form its baseline.
    r = check_regression(events, threshold=0.25)
    assert r.ok and not r.regressed
    assert r.n_baseline == 3


def test_check_incremental_takes_separated_from_full():
    """An incremental take writes only the delta — its written-bytes
    throughput must not pool with full takes' (either direction would
    corrupt the gate)."""
    events = [_synth(i, 1.0) for i in range(6)]
    # A healthy incremental take with low written-bytes throughput must
    # not flag against the full-take baseline...
    events.append(_synth(6, 0.3, incremental=True))
    r = check_regression(events, threshold=0.25)
    assert not r.ok and not r.regressed  # no incremental baseline yet
    # ...and must not dilute the full-take baseline either: a real
    # full-take regression still flags with incrementals interleaved.
    events += [_synth(7 + i, 0.3, incremental=True) for i in range(5)]
    events.append(_synth(12, 0.5))
    r = check_regression(events, threshold=0.25)
    assert r.regressed and r.baseline_median == pytest.approx(1.0)
    # And incremental runs gate against their own population.
    events.append(_synth(13, 0.1, incremental=True))
    r = check_regression(events, threshold=0.25)
    assert r.regressed and r.baseline_median == pytest.approx(0.3)


def test_check_latest_without_metric_is_not_silently_skipped():
    """A gate that grades a stale run while the newest one has no value
    for the metric would read as OK exactly when things broke."""
    events = [_synth(i, 1.0) for i in range(5)]
    no_metric = _synth(5, 1.0)
    no_metric["throughput_gbps"] = None
    events.append(no_metric)
    r = check_regression(events, threshold=0.25)
    assert not r.ok and not r.regressed
    assert "no value" in r.reason


def test_check_duration_metric_regresses_upward():
    events = [_synth(i, 1.0) for i in range(6)]
    slow = _synth(6, 1.0)
    slow["wall_s"] = 4.0
    events.append(slow)
    r = check_regression(events, metric="wall_s", threshold=0.25)
    assert r.regressed and "slower" in r.reason


def test_check_window_limits_baseline():
    events = [_synth(i, 10.0) for i in range(20)]
    events += [_synth(20 + i, 1.0) for i in range(5)]
    events.append(_synth(30, 0.9))
    r = check_regression(events, window=5, threshold=0.25)
    assert r.ok and not r.regressed  # old 10.0 era aged out of the window
    assert r.n_baseline == 5


# ------------------------------------------------------------------- CLI


def test_history_cli_table_json_and_check(history_env, capsys):
    for i in range(8):
        record_event(_synth(i, 1.0))
    assert main(["history"]) == 0
    out = capsys.readouterr().out
    assert "take" in out and "GB/s" in out
    assert main(["history", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["events"]) == 8
    assert main(["history", "--check"]) == 0
    capsys.readouterr()
    # Synthetic >threshold regression: exit 2 (the CI gate).
    record_event(_synth(8, 0.3))
    assert main(["history", "--check"]) == 2
    assert "REGRESSION" in capsys.readouterr().out
    assert main(["history", "--check", "--json"]) == 2
    doc = json.loads(capsys.readouterr().out)
    # The machine-readable contract NAMES each regressed metric and
    # carries its latest/baseline/window values.
    assert doc["regressed"] == ["throughput_gbps"]
    assert doc["ok"] is False
    (check,) = doc["checks"]
    assert check["metric"] == "throughput_gbps"
    assert check["latest"] == pytest.approx(0.3)
    assert check["baseline_median"] == pytest.approx(1.0)
    assert check["window"] == 20 and check["n_baseline"] >= 3
    # A cold-run-only outlier on top: exit 0.
    record_event(_synth(9, 0.2, cold=True))
    assert main(["history", "--check"]) == 0
    assert "cold" in capsys.readouterr().out
    # Loose threshold tolerates the earlier regression too.
    record_event(_synth(10, 0.9))
    assert main(["history", "--check", "--threshold", "0.95"]) == 0
    capsys.readouterr()


def test_history_cli_empty_and_insufficient(history_env, capsys):
    assert main(["history"]) == 3
    assert "no history" in capsys.readouterr().err
    assert main(["history", "--check"]) == 3
    capsys.readouterr()
    record_event(_synth(0, 1.0))
    record_event(_synth(1, 1.0))
    assert main(["history", "--check"]) == 3  # < min-baseline comparable
    assert "INSUFFICIENT" in capsys.readouterr().out


def test_history_cli_check_rejects_kind_all(history_env, capsys):
    record_event(_synth(0, 1.0))
    assert main(["history", "--kind", "all", "--check"]) == 1
    assert "one event kind" in capsys.readouterr().err


def test_history_cli_kind_filter(history_env, capsys):
    record_event(_synth(0, 1.0))
    record_event(_synth(1, 2.5, kind="bench", roofline_fraction=0.9))
    assert main(["history", "--kind", "bench", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert [e["kind"] for e in doc["events"]] == ["bench"]
    assert main(["history", "--kind", "all", "--json"]) == 0
    assert len(json.loads(capsys.readouterr().out)["events"]) == 2


def test_history_cli_multi_metric_check(history_env, capsys):
    """One gate invocation covers throughput AND p99 write latency:
    only the latency regresses; the JSON names it, exit 2 fires."""
    for i in range(8):
        record_event(_synth(i, 1.0, storage_write_p99_s=0.01))
    # Throughput fine, p99 write latency 10x (a *_s metric: upward).
    record_event(_synth(8, 1.0, storage_write_p99_s=0.1))
    rc = main(
        [
            "history",
            "--check",
            "--metric",
            "throughput_gbps",
            "--metric",
            "storage_write_p99_s",
            "--json",
        ]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert doc["regressed"] == ["storage_write_p99_s"]
    by_metric = {c["metric"]: c for c in doc["checks"]}
    assert by_metric["throughput_gbps"]["regressed"] is False
    assert by_metric["storage_write_p99_s"]["regressed"] is True
    assert by_metric["storage_write_p99_s"]["latest"] == pytest.approx(0.1)
    assert by_metric["storage_write_p99_s"]["baseline_median"] == pytest.approx(
        0.01
    )
    # Comma-splitting is equivalent to repeating the flag.
    assert (
        main(
            [
                "history",
                "--check",
                "--metric",
                "throughput_gbps,storage_write_p99_s",
            ]
        )
        == 2
    )
    assert "storage_write_p99_s" in capsys.readouterr().out


def test_history_cli_multi_metric_partial_coverage_passes(
    history_env, capsys
):
    """A metric absent from the events cannot be checked, but the gate
    passes while a checkable metric is green (a fleet upgrading to the
    histogram fields must not fail until old events age out)."""
    for i in range(8):
        record_event(_synth(i, 1.0))  # no storage_write_p99_s anywhere
    rc = main(
        [
            "history",
            "--check",
            "--metric",
            "throughput_gbps",
            "--metric",
            "storage_write_p99_s",
            "--json",
        ]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["regressed"] == []
    # ...and when NO metric can form a verdict: exit 3, as ever.
    assert (
        main(["history", "--check", "--metric", "no_such_metric"]) == 3
    )
    capsys.readouterr()


def test_event_from_summary_carries_write_latency_quantiles():
    """Take summaries with io_histograms produce gateable
    storage_write_p50_s/p99_s event fields (merged across plugins)."""
    from tpusnap.telemetry import IOStats

    st = IOStats()
    for _ in range(98):
        st.observe(0.004, 1 << 20)
    st.observe(0.4, 1 << 20)
    st.observe(0.4, 1 << 20)
    summary = {
        "rank": 0,
        "take_wall_s": 2.0,
        "counters": {"storage.bytes_written": 100 << 20},
        "io_histograms": {
            "write.FSStoragePlugin": st.to_dict(),
            "read.FSStoragePlugin": IOStats().to_dict(),
        },
    }
    ev = hist.event_from_summary("take", summary)
    assert ev["storage_write_p50_s"] <= 0.009
    assert ev["storage_write_p99_s"] >= 0.25
    # No histograms -> no fields (old events stay shaped as before).
    ev2 = hist.event_from_summary("take", {"take_wall_s": 1.0})
    assert "storage_write_p99_s" not in ev2


# --------------------------------------------------------- job identity


def test_events_carry_explicit_job_id_only(tmp_path, history_env):
    from tpusnap.knobs import override_job_id

    with override_job_id(None):
        Snapshot.take(str(tmp_path / "s1"), {"m": PytreeState(_state())})
    with override_job_id("exp-a"):
        Snapshot.take(str(tmp_path / "s2"), {"m": PytreeState(_state())})
    anon, named = load_history()
    # The host-pid DEFAULT is deliberately absent from history: it
    # changes every process and would empty every cross-run baseline.
    assert anon.get("job_id") is None
    assert named["job_id"] == "exp-a"


def test_check_regression_separates_job_ids():
    """Two named jobs interleaved in one shared history must never
    grade against each other; absent ids stay comparable (old
    histories keep their baselines)."""
    events = [_synth(i, 4.0, job_id="fast-job") for i in range(8)]
    events += [_synth(10 + i, 1.0, job_id="slow-job") for i in range(4)]
    # slow-job's latest 1.0 is healthy against ITS OWN 1.0 baseline —
    # pooling with fast-job's 4.0s would flag a phantom regression.
    r = check_regression(events, threshold=0.25)
    assert r.ok and not r.regressed
    assert r.n_baseline == 3
    # A real within-job regression still flags.
    events.append(_synth(20, 0.3, job_id="slow-job"))
    r = check_regression(events, threshold=0.25)
    assert r.regressed and r.baseline_median == pytest.approx(1.0)
    # Absent job_id (pre-knob histories + unset knob) stays one
    # comparable population.
    legacy = [_synth(i, 1.0) for i in range(6)] + [_synth(6, 0.5)]
    r = check_regression(legacy, threshold=0.25)
    assert r.regressed


# ----------------------------------------------- concurrent-append soak


_SOAK_CHILD = r"""
import os, sys, time
from tpusnap.history import record_event

path = sys.argv[1]
writer = int(sys.argv[2])
n = int(sys.argv[3])
for i in range(n):
    ev = {
        "v": 1,
        "ts": 1e9 + writer * 10000 + i,
        "kind": "soak",
        "rank": 0,
        "writer": writer,
        "i": i,
        "pad": "x" * 120,
    }
    assert record_event(ev, path=path) is not None
print("DONE", writer)
"""


def _run_soak_writers(path, n_writers, n_events, env):
    import subprocess
    import sys as _sys

    procs = [
        subprocess.Popen(
            [_sys.executable, "-c", _SOAK_CHILD, path, str(w), str(n_events)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for w in range(n_writers)
    ]
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, err[-800:]
        assert "DONE" in out


def _parse_all_lines(path):
    """Every line in the file must be a whole JSON event — the torn/
    interleaved-write failure mode this soak hunts."""
    events = []
    with open(path, "rb") as f:
        for ln in f.read().split(b"\n"):
            if not ln.strip():
                continue
            events.append(json.loads(ln))  # raises on any corrupt line
    return events


@pytest.mark.chaos
def test_concurrent_append_soak_no_corruption(tmp_path):
    """N processes hammering one history.jsonl via O_APPEND: every
    event lands exactly once, no interleaved or torn lines."""
    import os as _os

    path = str(tmp_path / "tele" / "history.jsonl")
    env = dict(
        _os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_HISTORY_MAX_BYTES=str(8 << 20),  # bound never trips
    )
    n_writers, n_events = 6, 40
    _run_soak_writers(path, n_writers, n_events, env)
    events = _parse_all_lines(path)
    assert len(events) == n_writers * n_events
    seen = {(e["writer"], e["i"]) for e in events}
    assert len(seen) == n_writers * n_events  # exactly once each
    for e in events:
        assert e["pad"] == "x" * 120  # payload intact, not spliced


@pytest.mark.chaos
def test_concurrent_append_soak_with_compaction(tmp_path):
    """Same soak with the size bound small enough that compaction runs
    CONCURRENTLY with other writers: every surviving line is still a
    whole, bit-exact event (compaction never keeps a torn line or
    tears a complete one), and the newest events survive it."""
    import os as _os

    path = str(tmp_path / "tele" / "history.jsonl")
    env = dict(
        _os.environ,
        JAX_PLATFORMS="cpu",
        # Knob floor is 64 KiB; ~170 B/event x 6 x 120 ≈ 120 KiB total,
        # so the bound trips repeatedly mid-soak.
        TPUSNAP_HISTORY_MAX_BYTES="1",
    )
    n_writers, n_events = 6, 120
    _run_soak_writers(path, n_writers, n_events, env)
    events = _parse_all_lines(path)
    assert events, "compaction must keep the newest lines"
    assert os.path.getsize(path) <= 64 * 1024 + 32 * 1024
    for e in events:
        assert e["kind"] == "soak"
        assert 0 <= e["writer"] < n_writers and 0 <= e["i"] < n_events
        assert e["pad"] == "x" * 120
    # The newest whole events survive: at least one writer's final
    # event (the last appends happen after the last compaction).
    finals = {(e["writer"], e["i"]) for e in events}
    assert any((w, n_events - 1) in finals for w in range(n_writers))
