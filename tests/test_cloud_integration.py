"""Real-bucket cloud-storage integration tests, secret/env gated.

Mirrors the reference's gated integration suites
(/root/reference/tests/test_s3_storage_plugin.py:29-49,
tests/test_gcs_storage_plugin.py): each class skips entirely unless its
bucket env var is set (CI provides them from repo secrets; local runs
skip), and a health-check fixture skips — not fails — on flaky access,
so missing cloud permissions never mask code regressions.

Covered per backend: raw plugin round-trip (write/read/ranged
read/delete), and a full Snapshot take -> verify -> restore cycle
against the real service.
"""

import os
import uuid

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot
from tpusnap.io_types import ReadIO, WriteIO

_S3_BUCKET = os.environ.get("TPUSNAP_TEST_S3_BUCKET")
_GCS_BUCKET = os.environ.get("TPUSNAP_TEST_GCS_BUCKET")


def _plugin_round_trip(url: str) -> None:
    import asyncio

    from tpusnap.storage_plugin import url_to_storage_plugin_in_event_loop

    loop = asyncio.new_event_loop()
    plugin = url_to_storage_plugin_in_event_loop(url, loop)
    try:
        payload = np.arange(100_000, dtype=np.uint8).tobytes()
        plugin.sync_write(WriteIO(path="blob", buf=payload), loop)
        read_io = ReadIO(path="blob")
        plugin.sync_read(read_io, loop)
        assert read_io.buf.getvalue() == payload
        ranged = ReadIO(path="blob", byte_range=(10, 50))
        plugin.sync_read(ranged, loop)
        assert ranged.buf.getvalue() == payload[10:50]
        loop.run_until_complete(plugin.delete("blob"))
    finally:
        plugin.sync_close(loop)
        loop.close()


def _snapshot_round_trip(url: str) -> None:
    state = StateDict(
        w=np.random.default_rng(0).standard_normal((256, 32)).astype(np.float32),
        step=7,
    )
    Snapshot.take(url, {"app": state})
    assert verify_snapshot(url).clean
    target = {"app": StateDict(w=np.zeros((256, 32), np.float32), step=0)}
    Snapshot(url).restore(target)
    assert target["app"]["step"] == 7
    assert np.array_equal(target["app"]["w"], state["w"])


def _health_check(url: str) -> None:
    """Probe the bucket once; unreachable/permission problems skip the
    suite instead of failing it (reference test_s3_storage_plugin.py:29-45)."""
    try:
        _plugin_round_trip(url + "/healthcheck")
    except Exception as e:  # noqa: BLE001 - any cloud failure means skip
        pytest.skip(f"cloud bucket {url} not usable from here: {e}")


@pytest.mark.s3_integration_test
@pytest.mark.skipif(not _S3_BUCKET, reason="TPUSNAP_TEST_S3_BUCKET not set")
class TestS3Integration:
    @pytest.fixture(autouse=True)
    def _prefix(self):
        pytest.importorskip("aiobotocore")
        self.url = f"s3://{_S3_BUCKET}/tpusnap_ci/{uuid.uuid4().hex}"
        _health_check(self.url)

    def test_plugin_round_trip(self):
        _plugin_round_trip(self.url + "/plugin")

    def test_snapshot_round_trip(self):
        _snapshot_round_trip(self.url + "/snap")


@pytest.mark.gcs_integration_test
@pytest.mark.skipif(not _GCS_BUCKET, reason="TPUSNAP_TEST_GCS_BUCKET not set")
class TestGCSIntegration:
    @pytest.fixture(autouse=True)
    def _prefix(self):
        pytest.importorskip("google.auth")
        self.url = f"gs://{_GCS_BUCKET}/tpusnap_ci/{uuid.uuid4().hex}"
        _health_check(self.url)

    def test_plugin_round_trip(self):
        _plugin_round_trip(self.url + "/plugin")

    def test_snapshot_round_trip(self):
        _snapshot_round_trip(self.url + "/snap")
