"""Partitioner + batcher unit tests (reference tests/test_partitioner.py,
tests/test_batcher.py patterns, without multi-process)."""

import asyncio
import os

import numpy as np
import pytest

from tpusnap.batcher import batch_read_requests, batch_write_requests
from tpusnap.io_preparers.array import ArrayBufferStager, ArrayIOPreparer
from tpusnap.io_types import BufferConsumer, ReadReq, WriteReq
from tpusnap.knobs import override_slab_size_threshold_bytes
from tpusnap.manifest import TensorEntry
from tpusnap.partitioner import (
    _greedy_assign,
    consolidate_replicated_entries,
)


def _tensor_entry(path, nbytes=100, replicated=True, location=None):
    return TensorEntry(
        location=location or f"replicated/{path}",
        serializer="buffer_protocol",
        dtype="uint8",
        shape=[nbytes],
        replicated=replicated,
    )


def test_greedy_assignment_balances():
    units = [(f"u{i}", [f"p{i}"], size) for i, size in enumerate([100, 90, 50, 40, 30, 10])]
    assignment = _greedy_assign(units, [0, 0, 0])
    loads = [0, 0, 0]
    for (key, _, size) in units:
        loads[assignment[key]] += size
    assert max(loads) - min(loads) <= 40  # largest-first greedy is balanced
    assert set(assignment.values()) == {0, 1, 2}


def test_greedy_respects_preexisting_load():
    units = [("u", ["p"], 10)]
    assignment = _greedy_assign(units, [1000, 0])
    assert assignment["u"] == 1


def test_consolidate_prefers_writer_batched_version():
    """The writer rank's slab-batched entry (location under batched/) must
    win over rank 0's unbatched copy — otherwise the manifest points at a
    blob nobody wrote (code-review regression)."""
    rank0 = {"m/w": _tensor_entry("m/w")}
    rank1_entry = _tensor_entry("m/w", location="batched/abc123")
    rank1_entry.byte_range = [0, 100]
    rank1 = {"m/w": rank1_entry}
    merged = consolidate_replicated_entries([rank0, rank1])
    assert merged["0/m/w"].location == "batched/abc123"
    assert merged["0/m/w"].byte_range == [0, 100]
    assert "1/m/w" not in merged


def test_consolidate_keeps_per_rank_entries():
    rank0 = {"m/x": _tensor_entry("m/x", replicated=False, location="0/m/x")}
    rank1 = {"m/x": _tensor_entry("m/x", replicated=False, location="1/m/x")}
    merged = consolidate_replicated_entries([rank0, rank1])
    assert merged["0/m/x"].location == "0/m/x"
    assert merged["1/m/x"].location == "1/m/x"


def test_batch_write_requests_packs_slabs(tmp_path):
    arrays = {f"a{i}": np.full(100, i, dtype=np.uint8) for i in range(10)}
    entries = {}
    write_reqs = []
    for name, arr in arrays.items():
        entry, reqs = ArrayIOPreparer.prepare_write(f"0/{name}", arr)
        entries[name] = entry
        write_reqs += reqs
    entries_list, reqs = batch_write_requests(list(entries.values()), write_reqs)
    assert len(reqs) == 1  # all ten 100B writes in one slab
    slab_req = reqs[0]
    assert slab_req.path.startswith("batched/")
    for entry in entries.values():
        assert entry.location == slab_req.path
        assert entry.byte_range is not None

    # stage the slab and check each member's byte range holds its data
    buf = asyncio.run(slab_req.buffer_stager.stage_buffer())
    mv = memoryview(buf)
    for name, arr in arrays.items():
        start, end = entries[name].byte_range
        assert bytes(mv[start:end]) == arr.tobytes()


def test_batch_write_respects_threshold():
    with override_slab_size_threshold_bytes(250):
        arrays = {f"a{i}": np.full(100, i, dtype=np.uint8) for i in range(5)}
        entries, write_reqs = {}, []
        for name, arr in arrays.items():
            entry, reqs = ArrayIOPreparer.prepare_write(f"0/{name}", arr)
            entries[name] = entry
            write_reqs += reqs
        _, reqs = batch_write_requests(list(entries.values()), write_reqs)
        # 5×100B with 250B slabs → 3 slabs (2+2+1); the singleton stays raw
        slab_reqs = [r for r in reqs if r.path.startswith("batched/")]
        assert len(slab_reqs) == 2
        assert len(reqs) == 3


class _CollectConsumer(BufferConsumer):
    def __init__(self, sink, key):
        self.sink, self.key = sink, key

    async def consume_buffer(self, buf, executor=None):
        self.sink[self.key] = bytes(buf)

    def get_consuming_cost_bytes(self):
        return 1


def test_batch_read_requests_merges_spans():
    sink = {}
    reqs = [
        ReadReq("loc", _CollectConsumer(sink, "a"), byte_range=(0, 10)),
        ReadReq("loc", _CollectConsumer(sink, "b"), byte_range=(10, 20)),
        ReadReq("loc", _CollectConsumer(sink, "c"), byte_range=(20, 32)),
        ReadReq("other", _CollectConsumer(sink, "d"), byte_range=(5, 9)),
    ]
    merged = batch_read_requests(reqs)
    assert len(merged) == 2
    span = [r for r in merged if r.path == "loc"][0]
    assert span.byte_range == (0, 32)
    data = bytes(range(32))
    asyncio.run(span.buffer_consumer.consume_buffer(data))
    assert sink["a"] == data[0:10] and sink["b"] == data[10:20] and sink["c"] == data[20:32]


def test_batch_read_skips_sparse_spans():
    sink = {}
    reqs = [
        ReadReq("loc", _CollectConsumer(sink, "a"), byte_range=(0, 10)),
        ReadReq("loc", _CollectConsumer(sink, "b"), byte_range=(1000, 1010)),
    ]
    merged = batch_read_requests(reqs)
    assert len(merged) == 2  # too sparse to merge


def test_batching_disabled_knob():
    from tpusnap.knobs import override_batching_disabled

    arrays = {f"a{i}": np.full(100, i, dtype=np.uint8) for i in range(4)}
    entries, write_reqs = {}, []
    for name, arr in arrays.items():
        entry, reqs = ArrayIOPreparer.prepare_write(f"0/{name}", arr)
        entries[name] = entry
        write_reqs += reqs
    with override_batching_disabled(True):
        _, reqs = batch_write_requests(list(entries.values()), write_reqs)
        assert len(reqs) == 4


class TestDeviceBatching:
    """Device-side slab packing (DeviceBatchedBufferStager) — the
    reference's GPUBatchedBufferStager analog done via XLA bitcast+concat
    and one DtoH DMA (reference batcher.py:101-159)."""

    def _prepare(self, arrays):
        entries, write_reqs = {}, []
        for name, arr in arrays.items():
            entry, reqs = ArrayIOPreparer.prepare_write(f"0/{name}", arr)
            entries[name] = entry
            write_reqs += reqs
        return entries, write_reqs

    def test_device_slab_packs_and_is_byte_exact(self):
        import jax.numpy as jnp

        from tpusnap.batcher import DeviceBatchedBufferStager

        arrays = {
            "f32": jnp.arange(32, dtype=jnp.float32),
            "bf16": jnp.arange(16, dtype=jnp.bfloat16),
            "i8": jnp.arange(-8, 8, dtype=jnp.int8),
            "bool": jnp.asarray([True, False] * 4),
        }
        entries, write_reqs = self._prepare(arrays)
        _, reqs = batch_write_requests(list(entries.values()), write_reqs)
        assert len(reqs) == 1
        assert isinstance(reqs[0].buffer_stager, DeviceBatchedBufferStager)
        buf = asyncio.run(reqs[0].buffer_stager.stage_buffer())
        mv = memoryview(buf).cast("B")
        for name, arr in arrays.items():
            start, end = entries[name].byte_range
            assert bytes(mv[start:end]) == np.asarray(arr).tobytes()

    def test_mixed_host_device_members_split_slabs(self):
        import jax.numpy as jnp

        from tpusnap.batcher import (
            BatchedBufferStager,
            DeviceBatchedBufferStager,
        )

        arrays = {
            "host0": np.full(100, 1, np.uint8),
            "dev0": jnp.arange(25, dtype=jnp.float32),
            "host1": np.full(100, 2, np.uint8),
            "dev1": jnp.arange(25, dtype=jnp.float32),
        }
        entries, write_reqs = self._prepare(arrays)
        _, reqs = batch_write_requests(list(entries.values()), write_reqs)
        kinds = {type(r.buffer_stager) for r in reqs}
        assert kinds == {BatchedBufferStager, DeviceBatchedBufferStager}
        assert len(reqs) == 2

    def test_device_batching_disabled_knob(self):
        import jax.numpy as jnp

        from tpusnap.batcher import BatchedBufferStager
        from tpusnap.knobs import override_device_batching_disabled

        arrays = {f"a{i}": jnp.arange(16, dtype=jnp.float32) for i in range(4)}
        entries, write_reqs = self._prepare(arrays)
        with override_device_batching_disabled(True):
            _, reqs = batch_write_requests(list(entries.values()), write_reqs)
        assert len(reqs) == 1
        assert isinstance(reqs[0].buffer_stager, BatchedBufferStager)

    def test_snapshot_roundtrip_with_device_batching(self, tmp_path):
        """End-to-end: sharded + replicated jax arrays, slabs packed on
        device, bit-identical restore."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from tpusnap import PytreeState, Snapshot

        mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("x", "y"))
        sharded = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("x", "y")),
        )
        state = {
            "sharded": sharded,
            "small_a": jnp.arange(10, dtype=jnp.bfloat16),
            "small_b": jnp.arange(20, dtype=jnp.int8),
        }
        app_state = {"m": PytreeState(dict(state))}
        Snapshot.take(str(tmp_path / "snap"), app_state)

        target = {
            "sharded": jax.device_put(
                jnp.zeros((8, 8), jnp.float32), NamedSharding(mesh, P("x", "y"))
            ),
            "small_a": jnp.zeros(10, jnp.bfloat16),
            "small_b": jnp.zeros(20, jnp.int8),
        }
        restored = {"m": PytreeState(target)}
        Snapshot(str(tmp_path / "snap")).restore(restored)
        for key, want in state.items():
            got = restored["m"].tree[key]
            assert np.asarray(got).tobytes() == np.asarray(want).tobytes()


def test_estimate_matches_prepared_entries():
    """Drift guard: estimate_write_loads' unit ids and costs must agree
    with what prepare_write actually produces — the partition plan is
    computed from the estimates, then applied to the prepared entries,
    and any disagreement degrades into duplicate writes."""
    import functools

    import jax.numpy as jnp

    from tpusnap.io_preparer import prepare_write
    from tpusnap.knobs import override_max_chunk_size_bytes
    from tpusnap.manifest import ChunkedTensorEntry, PrimitiveEntry, TensorEntry
    from tpusnap.partitioner import estimate_write_loads

    def cast(path, arr, tracing):
        return arr.astype(jnp.bfloat16) if path.endswith("big") else arr

    with override_max_chunk_size_bytes(16 * 1024):
        flattened = {
            "m/big": np.zeros((64, 256), np.float32),      # chunked, casts
            "m/small": np.arange(100, dtype=np.float32),   # dense
            "m/scalar": np.float32(3.5),                   # np.generic
            "m/lr": 0.1,                                   # primitive
            "m/blob": {1, 2, 3},                           # pickled object
        }
        units, base, traced = estimate_write_loads(
            flattened, sorted(flattened), array_prepare_func=cast
        )
        # The traced geometry covers every dense array leaf.
        assert set(traced) == {"m/big", "m/small", "m/scalar"}
        unit_ids = {u for u, _ in units}
        unit_costs = dict(units)

        for path, leaf in flattened.items():
            entry, _ = prepare_write(
                obj=leaf,
                logical_path=path,
                rank=0,
                replicated=True,
                array_prepare_func=functools.partial(cast, path),
            )
            if isinstance(entry, PrimitiveEntry):
                assert (path, 0) in units
            elif isinstance(entry, ChunkedTensorEntry):
                for i, chunk in enumerate(entry.chunks):
                    uid = f"{path}::{i}"
                    assert uid in unit_ids, (uid, sorted(unit_ids))
                    from tpusnap.serialization import tensor_nbytes

                    assert unit_costs[uid] == tensor_nbytes(
                        chunk.tensor.dtype, chunk.tensor.shape
                    )
                assert f"{path}::{len(entry.chunks)}" not in unit_ids
            elif isinstance(entry, TensorEntry):
                assert path in unit_ids
                from tpusnap.serialization import tensor_nbytes

                assert unit_costs[path] == tensor_nbytes(
                    entry.dtype, entry.shape
                )
            else:  # ObjectEntry: getsizeof approximation, just present
                assert path in unit_ids
