"""Smoke-run the examples as subprocesses — the examples are the canonical
user journeys (reference examples/simple_example.py etc.); an API drift that
breaks them must fail the suite, not a user.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name, *args, timeout=300):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, (
        f"{name} failed (rc={proc.returncode})\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )
    return proc.stdout


def test_simple_example_and_resume(tmp_path):
    out = _run_example("simple_example.py", "--work-dir", str(tmp_path))
    assert "epoch 4" in out
    # Resume from epoch 2's snapshot: the loop must continue at epoch 3.
    out = _run_example(
        "simple_example.py",
        "--work-dir",
        str(tmp_path),
        "--resume-from",
        str(tmp_path / "epoch_2"),
    )
    assert "resumed" in out and "at epoch 2" in out
    assert "epoch 3" in out and "epoch 4" in out


def test_transformer_example(tmp_path):
    _run_example("transformer_example.py", "--work-dir", str(tmp_path))
    # Resume from the last epoch snapshot: exercises async_restore
    # (reads overlap setup) in the canonical flagship journey.
    import glob

    snaps = sorted(glob.glob(str(tmp_path / "epoch_*")))
    assert snaps, "example produced no snapshots"
    out = _run_example(
        "transformer_example.py",
        "--work-dir",
        str(tmp_path),
        "--resume-from",
        snaps[-1],
    )
    assert "resumed at epoch" in out


@pytest.mark.distributed
def test_distributed_example(tmp_path):
    _run_example("distributed_example.py", "--work-dir", str(tmp_path))


def test_incremental_example(tmp_path):
    out = _run_example("incremental_example.py", "--work-dir", str(tmp_path))
    assert "incremental on" in out
    assert "0 corrupt" in out
    assert "bit-exact" in out
