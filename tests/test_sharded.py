"""NamedSharding save/restore + resharding matrix on an 8-device CPU mesh,
mirroring the reference's tests/test_sharded_tensor_resharding.py:35-108
(5×5 sharding-spec matrix) — but over jax NamedShardings, which cover
DP/FSDP/TP/SP/EP uniformly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpusnap import Snapshot, StateDict
from tpusnap.knobs import override_max_shard_size_bytes
from tpusnap.manifest import ShardedEntry, TensorEntry

SHAPE = (16, 12)


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("x", "y"))


def _make(sharding):
    arr = jnp.arange(np.prod(SHAPE), dtype=jnp.float32).reshape(SHAPE)
    return jax.device_put(arr, sharding)


SPECS = [
    P("x"),  # row-sharded (FSDP-style)
    P(None, "y"),  # col-sharded (TP-style)
    P("x", "y"),  # 2-D grid
    P(("x", "y"),),  # fully sharded rows over all 8 devices
    P("y"),  # row-sharded over y, replicated over x (hybrid DP)
]


@pytest.mark.parametrize("src_spec", SPECS, ids=[str(s) for s in SPECS])
@pytest.mark.parametrize("dst_spec", SPECS, ids=[str(s) for s in SPECS])
def test_reshard_matrix(tmp_path, src_spec, dst_spec):
    mesh = _mesh()
    src = _make(NamedSharding(mesh, src_spec))
    snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=src)})

    dst = {"s": StateDict(a=_make(NamedSharding(mesh, dst_spec)) * 0)}
    snap.restore(dst)
    out = dst["s"]["a"]
    assert out.sharding.is_equivalent_to(NamedSharding(mesh, dst_spec), out.ndim)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(src))


def test_replica_dedup_in_manifest(tmp_path):
    """P('y') on a (4,2) mesh has 2 distinct pieces replicated 4×; only
    replica 0 of each piece may be written (reference analog: write-load
    dedup of DDP replicas)."""
    mesh = _mesh()
    src = _make(NamedSharding(mesh, P("y")))
    snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=src)})
    entry = snap.get_manifest()["0/s/a"]
    assert isinstance(entry, ShardedEntry)
    assert len(entry.shards) == 2
    offsets = sorted(tuple(s.offsets) for s in entry.shards)
    assert offsets == [(0, 0), (8, 0)]


def test_shard_subdivision(tmp_path):
    """Shards above max_shard_size split along their largest dim
    (reference subdivide_shard, sharded_tensor.py:47-76)."""
    mesh = _mesh()
    with override_max_shard_size_bytes(64):  # each (4,12) f32 shard = 192B
        src = _make(NamedSharding(mesh, P("x")))
        snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=src)})
        entry = snap.get_manifest()["0/s/a"]
        assert len(entry.shards) > 4  # subdivided
        dst = {"s": StateDict(a=_make(NamedSharding(mesh, P(None, "y"))) * 0)}
        snap.restore(dst)
        np.testing.assert_array_equal(np.asarray(dst["s"]["a"]), np.asarray(src))


def test_sharded_to_dense_read_object(tmp_path):
    mesh = _mesh()
    src = _make(NamedSharding(mesh, P("x", "y")))
    snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=src)})
    dense = snap.read_object("0/s/a")
    assert isinstance(dense, np.ndarray)
    np.testing.assert_array_equal(dense, np.asarray(src))


def test_dense_to_sharded_restore(tmp_path):
    """Snapshot taken with a dense array restores into a sharded target."""
    arr = jnp.arange(np.prod(SHAPE), dtype=jnp.float32).reshape(SHAPE)
    snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=arr)})
    mesh = _mesh()
    dst = {"s": StateDict(a=_make(NamedSharding(mesh, P("x", "y"))) * 0)}
    snap.restore(dst)
    out = dst["s"]["a"]
    assert len(out.sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))


def test_odd_shape_resharding(tmp_path):
    """Non-power-of-two dims across different axes. (JAX requires dims to
    divide the mesh axis — truly uneven shards are unconstructible — but
    odd factors still exercise non-aligned offset arithmetic.)"""
    mesh = _mesh()
    arr = jnp.arange(12 * 6, dtype=jnp.int32).reshape(12, 6)
    src = jax.device_put(arr, NamedSharding(mesh, P("x")))
    snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=src)})
    dst = {"s": StateDict(a=jax.device_put(jnp.zeros((12, 6), jnp.int32),
                                           NamedSharding(mesh, P(None, "y"))))}
    snap.restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["s"]["a"]), np.asarray(arr))


def test_sharded_bf16_bit_exact(tmp_path):
    mesh = _mesh()
    bits = np.arange(16 * 128, dtype=np.uint16).reshape(16, 128)
    import ml_dtypes

    arr = jnp.asarray(bits.view(ml_dtypes.bfloat16))
    src = jax.device_put(arr, NamedSharding(mesh, P("x")))
    snap = Snapshot.take(str(tmp_path / "snap"), {"s": StateDict(a=src)})
    dst = {"s": StateDict(a=jax.device_put(jnp.zeros((16, 128), jnp.bfloat16),
                                           NamedSharding(mesh, P("x", "y"))))}
    snap.restore(dst)
    assert np.asarray(dst["s"]["a"]).tobytes() == np.asarray(src).tobytes()


class TestShardedSaveTimeTransform:
    """The save-time transform threads through the SHARDED preparer
    (reference io_preparer.py:100-106, sharded_tensor.py:133,159): on
    TPU essentially all interesting training state is
    NamedSharding-sharded, so ``cast_on_save`` must reach it."""

    def _take_bf16(self, tmp_path, spec=P("x")):
        import ml_dtypes

        from tpusnap.transforms import cast_on_save

        mesh = _mesh()
        w = (
            np.linspace(-2, 2, np.prod(SHAPE))
            .astype(np.float32)
            .reshape(SHAPE)
        )
        src = jax.device_put(jnp.asarray(w), NamedSharding(mesh, spec))
        path = str(tmp_path / "snap")
        Snapshot.take(
            path,
            {"s": StateDict(w=src)},
            _custom_array_prepare_func=cast_on_save({"**": jnp.bfloat16}),
        )
        expect = w.astype(ml_dtypes.bfloat16)
        return path, mesh, w, expect

    def test_manifest_records_stored_dtype(self, tmp_path):
        path, _, _, _ = self._take_bf16(tmp_path)
        entry = Snapshot(path).get_manifest()["0/s/w"]
        assert isinstance(entry, ShardedEntry)
        assert entry.dtype == "bfloat16"
        assert all(s.tensor.dtype == "bfloat16" for s in entry.shards)
        # Stored blob bytes are half-width: (4,12) bf16 shard = 96 bytes.
        from tpusnap.serialization import tensor_nbytes

        assert all(
            tensor_nbytes(s.tensor.dtype, s.tensor.shape)
            == np.prod(s.sizes) * 2
            for s in entry.shards
        )

    def test_restore_upcasts_into_f32_sharded_target(self, tmp_path):
        path, mesh, _, expect = self._take_bf16(tmp_path)
        # Full-precision training target with a DIFFERENT sharding:
        # reshard + upcast in one restore.
        dst = {
            "s": StateDict(
                w=jax.device_put(
                    jnp.zeros(SHAPE, jnp.float32),
                    NamedSharding(mesh, P(None, "y")),
                )
            )
        }
        Snapshot(path).restore(dst)
        out = dst["s"]["w"]
        assert out.dtype == jnp.float32
        assert out.sharding.is_equivalent_to(
            NamedSharding(mesh, P(None, "y")), out.ndim
        )
        np.testing.assert_array_equal(
            np.asarray(out), expect.astype(np.float32)
        )

    def test_restore_bit_exact_into_bf16_target(self, tmp_path):
        path, mesh, _, expect = self._take_bf16(tmp_path)
        dst = {
            "s": StateDict(
                w=jax.device_put(
                    jnp.zeros(SHAPE, jnp.bfloat16),
                    NamedSharding(mesh, P("x")),
                )
            )
        }
        Snapshot(path).restore(dst)
        assert np.asarray(dst["s"]["w"]).tobytes() == expect.tobytes()

    def test_read_object_dense_returns_stored_dtype(self, tmp_path):
        path, _, _, expect = self._take_bf16(tmp_path)
        out = Snapshot(path).read_object("0/s/w")
        assert str(out.dtype) == "bfloat16"
        assert np.asarray(out).tobytes() == expect.tobytes()

    def test_np_dense_target_upcasts_in_place(self, tmp_path):
        path, _, _, expect = self._take_bf16(tmp_path)
        target = np.zeros(SHAPE, np.float32)
        out = Snapshot(path).read_object("0/s/w", obj_out=target)
        assert out is target
        np.testing.assert_array_equal(target, expect.astype(np.float32))

    def test_subdivision_uses_stored_itemsize(self, tmp_path):
        """max_shard_size applies to the blob as WRITTEN: a 192-byte f32
        shard casting to 96 bytes of bf16 fits a 96-byte cap unsplit."""
        with override_max_shard_size_bytes(96):
            path, _, _, _ = self._take_bf16(tmp_path)
        entry = Snapshot(path).get_manifest()["0/s/w"]
        # P("x") on the 4x2 mesh -> 4 distinct (4,12) pieces; each is
        # 96 B stored, exactly at the cap -> no subdivision.
        assert len(entry.shards) == 4
