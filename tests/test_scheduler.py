"""Scheduler pipeline tests: budget gating, overlap, failure propagation."""

import asyncio
import os

import pytest

from tpusnap.io_types import (
    BufferConsumer,
    BufferStager,
    ReadReq,
    WriteReq,
)
from tpusnap.knobs import override_memory_budget_bytes
from tpusnap.scheduler import (
    PendingIOWork,
    execute_read_reqs,
    execute_write_reqs,
    get_process_memory_budget_bytes,
    sync_execute_write_reqs,
)
from tpusnap.storage_plugins.fs import FSStoragePlugin


class TrackingStager(BufferStager):
    """Stager that tracks global concurrent staging cost."""

    live_cost = 0
    peak_cost = 0

    def __init__(self, data: bytes, cost: int):
        self.data = data
        self.cost = cost

    async def stage_buffer(self, executor=None):
        TrackingStager.live_cost += self.cost
        TrackingStager.peak_cost = max(
            TrackingStager.peak_cost, TrackingStager.live_cost
        )
        await asyncio.sleep(0.01)
        # buffer stays "live" until the write completes; we approximate by
        # decrementing at write time via WriteTracker below
        return self.data

    def get_staging_cost_bytes(self) -> int:
        return self.cost


class ByteConsumer(BufferConsumer):
    def __init__(self, sink: dict, key: str, cost: int = 0):
        self.sink = sink
        self.key = key
        self.cost = cost

    async def consume_buffer(self, buf, executor=None) -> None:
        self.sink[self.key] = bytes(buf)

    def get_consuming_cost_bytes(self) -> int:
        return self.cost


class FaultyStager(BufferStager):
    async def stage_buffer(self, executor=None):
        raise RuntimeError("staging boom")

    def get_staging_cost_bytes(self) -> int:
        return 10


class FaultyPlugin(FSStoragePlugin):
    async def write(self, write_io) -> None:
        raise OSError("storage boom")


def test_write_then_read_roundtrip(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    blobs = {f"blob{i}": os.urandom(1000 + i) for i in range(40)}
    write_reqs = [
        WriteReq(path=k, buffer_stager=TrackingStager(v, cost=len(v)))
        for k, v in blobs.items()
    ]
    loop = asyncio.new_event_loop()
    try:
        pending = sync_execute_write_reqs(
            write_reqs, plugin, memory_budget_bytes=1 << 30, rank=0, event_loop=loop
        )
        assert isinstance(pending, PendingIOWork)
        pending.sync_complete(loop)

        sink = {}
        read_reqs = [
            ReadReq(path=k, buffer_consumer=ByteConsumer(sink, k, cost=len(v)))
            for k, v in blobs.items()
        ]
        loop.run_until_complete(
            execute_read_reqs(read_reqs, plugin, 1 << 30, rank=0)
        )
        assert sink == blobs
    finally:
        loop.close()


def test_budget_gates_staging(tmp_path):
    """With a budget of 2 units and 8 one-unit items, peak concurrent
    staging cost must never exceed the budget."""
    TrackingStager.live_cost = 0
    TrackingStager.peak_cost = 0
    plugin = FSStoragePlugin(root=str(tmp_path))

    unit = 1000
    blobs = {f"b{i}": os.urandom(unit) for i in range(8)}

    class DecrementingPlugin(FSStoragePlugin):
        async def write(self, write_io) -> None:
            await super().write(write_io)
            TrackingStager.live_cost -= len(write_io.buf)

    plugin = DecrementingPlugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=k, buffer_stager=TrackingStager(v, cost=unit))
        for k, v in blobs.items()
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs, plugin, memory_budget_bytes=2 * unit, rank=0
        )
        await pending.complete()

    asyncio.run(go())
    assert TrackingStager.peak_cost <= 2 * unit


def test_over_budget_item_still_runs(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    data = os.urandom(5000)
    write_reqs = [
        WriteReq(path="huge", buffer_stager=TrackingStager(data, cost=len(data)))
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs, plugin, memory_budget_bytes=10, rank=0
        )
        await pending.complete()

    asyncio.run(go())  # must not deadlock
    assert (tmp_path / "huge").read_bytes() == data


def test_staging_failure_propagates(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    write_reqs = [WriteReq(path="x", buffer_stager=FaultyStager())]

    async def go():
        pending = await execute_write_reqs(write_reqs, plugin, 1 << 30, rank=0)
        await pending.complete()

    with pytest.raises(RuntimeError, match="staging boom"):
        asyncio.run(go())


def test_storage_failure_propagates_on_complete(tmp_path):
    plugin = FaultyPlugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path="x", buffer_stager=TrackingStager(b"abc", cost=3))
    ]

    async def go():
        pending = await execute_write_reqs(write_reqs, plugin, 1 << 30, rank=0)
        await pending.complete()

    with pytest.raises(OSError, match="storage boom"):
        asyncio.run(go())


def test_memory_budget_env_override():
    with override_memory_budget_bytes(12345):
        assert get_process_memory_budget_bytes() == 12345
    budget = get_process_memory_budget_bytes()
    assert 0 < budget <= 32 * 1024**3


def test_read_budget_gating(tmp_path):
    """Reads with consuming cost above budget must still complete (one at a
    time) and all data must arrive."""
    plugin = FSStoragePlugin(root=str(tmp_path))
    blobs = {f"r{i}": os.urandom(500) for i in range(6)}
    loop = asyncio.new_event_loop()
    try:
        for k, v in blobs.items():
            from tpusnap.io_types import WriteIO

            plugin.sync_write(WriteIO(path=k, buf=v), event_loop=loop)
        sink = {}
        read_reqs = [
            ReadReq(path=k, buffer_consumer=ByteConsumer(sink, k, cost=400))
            for k in blobs
        ]
        loop.run_until_complete(execute_read_reqs(read_reqs, plugin, 450, rank=0))
        assert sink == blobs
    finally:
        loop.close()


def test_reporter_stats_and_log_split(tmp_path, caplog, monkeypatch):
    """Reporter parity (reference scheduler.py:96-175): the final summary
    logs the staging-time vs total-time split, periodic reports carry
    per-stage pipeline counts + remaining budget, and the split is
    published via LAST_EXECUTION_STATS for benchmarks."""
    import logging

    from tpusnap import scheduler as sched

    monkeypatch.setattr(sched, "_REPORT_INTERVAL_SEC", 0.0)
    plugin = FSStoragePlugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=f"w{i}", buffer_stager=TrackingStager(os.urandom(256), 256))
        for i in range(5)
    ]
    loop = asyncio.new_event_loop()
    try:
        with caplog.at_level(logging.INFO, logger="tpusnap.scheduler"):
            pending = sync_execute_write_reqs(
                write_reqs, plugin, 10_000, rank=0, event_loop=loop
            )
            pending.sync_complete(loop)
    finally:
        loop.close()
    stats = sched.LAST_EXECUTION_STATS["write"]
    assert stats["reqs"] == 5
    assert stats["bytes"] == 5 * 256
    assert stats["staging_s"] is not None
    assert 0 <= stats["staging_s"] <= stats["total_s"]
    text = caplog.text
    assert "staging" in text and "residual I/O" in text
    # Per-stage counts + budget appear in at least one periodic report.
    assert "ready_for_staging=" in text and "io=" in text
    assert "budget" in text


@pytest.mark.parametrize("warm_pool", [False, True])
def test_pooled_buffers_do_not_permanently_debit_budget(tmp_path, warm_pool):
    """ADVICE r4: buffers the staging pool retains after a write must
    not withhold their budget credit — withholding re-debited the same
    resident bytes every reuse cycle, so a budget-capped take whose
    cumulative pooled-clone bytes exceeded the budget degraded to
    fully serialized stage-then-write. The budget governs in-flight
    buffers only (the pool is bounded by its own cap), so staging must
    keep overlapping storage I/O through the whole request list — both
    from a cold pool and from a PRE-WARMED pool (a steady-state
    checkpoint loop's second take: charging parked bytes against the
    take while reuse re-charges them via staging_cost would serialize
    the warm case)."""
    import time

    import tpusnap._staging_pool as sp

    sp.clear()
    unit = 1 << 16
    n = 10
    spans = {}

    class PoolStager(BufferStager):
        def __init__(self, path: str):
            self.path = path

        async def stage_buffer(self, executor=None):
            spans[self.path] = [time.monotonic(), None]
            buf = sp.acquire(unit)
            await asyncio.sleep(0.003)
            return buf

        def get_staging_cost_bytes(self) -> int:
            return unit

    class SlowPlugin(FSStoragePlugin):
        async def write(self, write_io) -> None:
            await asyncio.sleep(0.02)
            await super().write(write_io)
            spans[write_io.path][1] = time.monotonic()

    if warm_pool:
        # Park `n` unit-sized buffers, as a previous take would have.
        parked = [sp.acquire(unit) for _ in range(4)]
        for b in parked:
            assert sp.release(b) is True
        del parked

    plugin = SlowPlugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=f"b{i}", buffer_stager=PoolStager(f"b{i}"))
        for i in range(n)
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs,
            plugin,
            memory_budget_bytes=2 * unit + unit // 2,
            rank=0,
        )
        await pending.complete()

    try:
        asyncio.run(go())
    finally:
        sp.clear()

    assert all(e is not None for _, e in spans.values())
    # Look only at the SECOND half (by stage start): the old accounting
    # was correct early and only seized up once retained bytes crossed
    # the budget.
    tail = sorted(spans.values())[n // 2 :]
    overlaps = sum(
        1
        for i, a in enumerate(tail)
        for j, b in enumerate(tail)
        if i != j and a[0] < b[1] and b[0] < a[1]
    )
    assert overlaps > 0, (
        "budget-capped pooled take degraded to serialized stage-then-write"
    )


def test_prioritize_staging_defers_io_until_staging_done(tmp_path):
    """Async takes: no storage I/O may start while staging can still
    proceed — write-path CPU inside the staging window is exactly the
    blocked-time the async path exists to avoid. Writes drain via
    PendingIOWork after."""
    import time

    events = []

    class Stager(BufferStager):
        def __init__(self, data):
            self.data = data

        async def stage_buffer(self, executor=None):
            await asyncio.sleep(0.01)
            events.append(("stage", time.monotonic()))
            return self.data

        def get_staging_cost_bytes(self) -> int:
            return len(self.data)

    class Plugin(FSStoragePlugin):
        async def write(self, write_io) -> None:
            events.append(("write", time.monotonic()))
            await super().write(write_io)

    plugin = Plugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=f"b{i}", buffer_stager=Stager(os.urandom(64)))
        for i in range(8)
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs, plugin, 1 << 30, rank=0, prioritize_staging=True
        )
        assert not pending.scheduler.io_tasks  # nothing dispatched in the window
        assert len(pending.scheduler.ready_for_io) == 8
        await pending.complete()

    asyncio.run(go())
    last_stage = max(t for k, t in events if k == "stage")
    first_write = min(t for k, t in events if k == "write")
    assert first_write >= last_stage, "write started inside the staging window"
    assert sum(1 for k, _ in events if k == "write") == 8


def test_prioritize_staging_budget_starved_opens_io_gate(tmp_path):
    """When the budget cannot hold all staged buffers at once, the I/O
    gate MUST open mid-staging (write completions are the only budget
    source): writes interleave with staging, resident staged bytes stay
    bounded by the budget (plus the ≥1-admission allowance), and the
    take completes. Guards the r5 review finding where the over-budget
    admission fallback kept refilling staging past gated ready-for-io
    buffers, holding every staged buffer resident."""
    import time

    unit = 1000
    events = []
    live = {"n": 0, "peak": 0}

    class Stager(BufferStager):
        def __init__(self, data):
            self.data = data

        async def stage_buffer(self, executor=None):
            await asyncio.sleep(0.005)
            live["n"] += 1
            live["peak"] = max(live["peak"], live["n"])
            events.append(("stage", time.monotonic()))
            return self.data

        def get_staging_cost_bytes(self) -> int:
            return unit

    class Plugin(FSStoragePlugin):
        async def write(self, write_io) -> None:
            events.append(("write", time.monotonic()))
            await super().write(write_io)
            live["n"] -= 1

    plugin = Plugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=f"b{i}", buffer_stager=Stager(os.urandom(unit)))
        for i in range(10)
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs, plugin, memory_budget_bytes=2 * unit, rank=0,
            prioritize_staging=True,
        )
        await pending.complete()

    asyncio.run(go())
    for i in range(10):
        assert (tmp_path / f"b{i}").exists()
    # The gate opened mid-staging: some write started before staging
    # finished (10 one-unit buffers can never fit a 2-unit budget).
    last_stage = max(t for k, t in events if k == "stage")
    first_write = min(t for k, t in events if k == "write")
    assert first_write < last_stage, "I/O gate never opened under starvation"
    # Resident staged-but-unwritten buffers bounded by the budget (in
    # units) plus the single ≥1-admission allowance.
    assert live["peak"] <= 3, f"budget unenforced: peak {live['peak']} buffers resident"
