"""Rank-failure tolerance: lease liveness, fast-fail waits, degraded
commit, and the rank-scoped chaos faults that drive them.

Unit layer (fake clocks, MemoryKVStore — zero sleeps): lease expiry
semantics, terminal-state immunity, watcher exclusion, the knob
routing of the historical barrier-timeout literals, the deterministic
adoption re-plan, degrade eligibility, and the chaos-spec extensions
(``rank=``, ``wedge=``).

Multi-process layer (real jax.distributed worlds, ``distributed``
mark): the crash matrix of ISSUE 15 — SIGKILL one rank of 2 mid-stage,
mid-write and inside the commit barrier and assert the survivor raises
:class:`RankFailedError` naming the dead rank within 3x the lease TTL
(vs the 600 s barrier timeout before); a degrade-mode replicated-only
take that commits with one rank dead and restores bit-exact; and a
sharded-state death that aborts to a torn state whose fsck/timeline
verdicts name the dead rank and whose retake salvages the survivor's
completed blobs.
"""

import os
import re
import signal
import time

import numpy as np
import pytest

from tpusnap.dist_store import LinearBarrier, MemoryKVStore
from tpusnap.knobs import (
    get_barrier_timeout_s,
    get_commit_barrier_timeout_s,
    get_liveness_ttl_s,
    get_rank_failure_policy,
    override_barrier_timeout_s,
    override_liveness,
)
from tpusnap.liveness import (
    LeasePublisher,
    LivenessMonitor,
    RankFailedError,
    lease_key,
)

# ------------------------------------------------------------ unit layer


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _world(kv, take_id, world_size, ttl, clock):
    pubs = [LeasePublisher(kv, take_id, r) for r in range(world_size)]
    for p in pubs:
        p.publish()
    mon = LivenessMonitor(
        kv, take_id, 0, world_size, ttl_s=ttl, clock=clock
    )
    return pubs, mon


def test_monitor_alive_while_leases_advance():
    kv, clock = MemoryKVStore(), FakeClock()
    pubs, mon = _world(kv, "t1", 3, ttl=10.0, clock=clock)
    for _ in range(5):
        clock.advance(5.0)
        for p in pubs:
            p.publish()
        mon.check()  # advancing leases: never raises
    assert mon.expired() == []


def test_monitor_expires_silent_rank_and_names_it():
    kv, clock = MemoryKVStore(), FakeClock()
    pubs, mon = _world(kv, "t2", 3, ttl=10.0, clock=clock)
    mon.check()  # anchor: first observation of every lease
    # Rank 2 stops publishing; 1 keeps beating. 8s in: still fine.
    clock.advance(4.0)
    pubs[1].publish()
    mon.check()
    clock.advance(4.0)
    pubs[1].publish()
    mon.check()
    # 12s since rank 2's lease advanced: past the 10s TTL.
    clock.advance(4.0)
    pubs[1].publish()
    with pytest.raises(RankFailedError) as ei:
        mon.check()
    assert ei.value.ranks == [2]
    assert "2" in str(ei.value)
    assert mon.dead_ranks() == [2]


def test_monitor_never_expires_self_or_terminal():
    kv, clock = MemoryKVStore(), FakeClock()
    pubs, mon = _world(kv, "t3", 2, ttl=5.0, clock=clock)
    # Rank 1 exits the take deliberately: terminal lease, not a death.
    pubs[1].finish("committed")
    clock.advance(60.0)
    mon.check()  # no raise: rank 0 is self, rank 1 is terminal
    assert mon.expired() == []


def test_monitor_grace_for_never_published_rank():
    kv, clock = MemoryKVStore(), FakeClock()
    # Rank 1 never publishes at all (killed pre-first-beat).
    mon = LivenessMonitor(kv, "t4", 0, 2, ttl_s=5.0, clock=clock)
    clock.advance(7.0)
    assert mon.expired() == []  # within the 2x-TTL grace
    clock.advance(5.0)
    assert mon.expired() == [1]


def test_monitor_exclude_acknowledged_dead():
    kv, clock = MemoryKVStore(), FakeClock()
    pubs, mon = _world(kv, "t5", 3, ttl=5.0, clock=clock)
    mon.check()  # anchor the first observation
    clock.advance(20.0)
    pubs[0].publish()
    assert sorted(mon.expired()) == [1, 2]
    # The degraded commit's barriers exclude the acknowledged dead set.
    mon.check(exclude={1, 2})  # no raise
    with pytest.raises(RankFailedError):
        mon.check(exclude={1})


def test_lease_tick_hook_and_terminal_mapping():
    kv = MemoryKVStore()
    pub = LeasePublisher(kv, "t6", 0)
    hook = pub.make_tick_hook()
    hook(None)
    hook({"state": "running"})
    import json

    rec = json.loads(kv.try_get(lease_key("t6", 0)))
    assert rec["state"] == "live" and rec["seq"] == 2
    hook({"state": "committed"})
    rec = json.loads(kv.try_get(lease_key("t6", 0)))
    assert rec["state"] == "done"
    pub.cleanup()
    assert kv.try_get(lease_key("t6", 0)) is None


# ----------------------------------------------- knob routing (satellite)


def test_barrier_timeout_knob_routes_everywhere():
    assert get_barrier_timeout_s() == 600.0
    assert get_commit_barrier_timeout_s() == 1800.0
    with override_barrier_timeout_s(42):
        assert get_barrier_timeout_s() == 42.0
        assert get_commit_barrier_timeout_s() == 126.0
        b = LinearBarrier(MemoryKVStore(), "kt", 0, 2)
        assert b.timeout_sec == 42.0
        from tpusnap.comm import _default_timeout_ms

        assert _default_timeout_ms() == 42_000
        from tpusnap.dist_store import KVStore

        store = MemoryKVStore()
        store.set("x", b"1")
        assert store.get("x") == b"1"  # default timeout resolves


def test_liveness_knobs():
    assert get_liveness_ttl_s() == 15.0
    with override_liveness(ttl_s=0):
        assert get_liveness_ttl_s() == 0.0  # disabled
    with override_liveness(ttl_s=0.01):
        # Floor: 4x the heartbeat interval.
        assert get_liveness_ttl_s() == pytest.approx(2.0)
    assert get_rank_failure_policy() == "abort"
    with override_liveness(policy="degrade"):
        assert get_rank_failure_policy() == "degrade"
    with override_liveness(policy="bogus"):
        assert get_rank_failure_policy() == "abort"  # warn-once fallback


# ------------------------------------------------- subset LinearBarrier


def test_linear_barrier_subset_ranks():
    import threading

    store = MemoryKVStore()
    done = []

    def member(rank):
        b = LinearBarrier(store, "sub", rank, 4, ranks=[0, 2], timeout_sec=10)
        assert b.leader_rank == 0
        b.arrive()
        b.depart()
        done.append(rank)

    t = threading.Thread(target=member, args=(2,))
    t.start()
    member(0)
    t.join(timeout=10)
    assert sorted(done) == [0, 2]


def test_linear_barrier_rejects_non_member():
    with pytest.raises(ValueError):
        LinearBarrier(MemoryKVStore(), "nm", 1, 4, ranks=[0, 2])


def test_linear_barrier_watcher_raises_rank_failure():
    kv, clock = MemoryKVStore(), FakeClock()
    pubs, mon = _world(kv, "t7", 2, ttl=5.0, clock=clock)
    mon.check()  # anchor the first observation
    b = LinearBarrier(
        MemoryKVStore(),
        "wf",
        0,
        2,
        timeout_sec=30,
        watchers=[mon.check],
    )
    clock.advance(20.0)
    with pytest.raises(RankFailedError):
        b.arrive()  # leader waits for rank 1's arrive; watcher fires


# -------------------------------------------------- adoption re-planning


def test_reassign_dead_units_deterministic_round_robin():
    from tpusnap.partitioner import reassign_dead_units

    assignment = {"a": 1, "b": 1, "c": 0, "d::0": 1, "d::1": 2}
    plan = reassign_dead_units(assignment, dead_ranks=[1], live_ranks=[0, 2])
    assert set(plan) == {"a", "b", "d::0"}
    # Round-robin over sorted live ranks, in sorted unit order.
    assert plan == {"a": 0, "b": 2, "d::0": 0}
    # Identical on every caller (pure function of its inputs).
    assert plan == reassign_dead_units(assignment, [1], [2, 0])


def test_degrade_eligibility_rule():
    from tpusnap.manifest import (
        DictEntry,
        ObjectEntry,
        PrimitiveEntry,
        ShardedEntry,
        TensorEntry,
    )
    from tpusnap.snapshot import _degrade_eligible

    repl = TensorEntry(
        location="app/w", serializer="raw", dtype="float32",
        shape=[2], replicated=True,
    )
    assert _degrade_eligible([{"app/w": repl, "app": DictEntry(keys=["w"])}]) is None
    sharded = ShardedEntry(shards=[], dtype="float32", shape=[2, 2])
    reason = _degrade_eligible([{"app/w": repl, "app/s": sharded}])
    assert reason is not None and "unique" in reason
    prim = PrimitiveEntry(
        dtype="int", layout="", serialized_value="3", replicated=False
    )
    reason = _degrade_eligible([{"app/step": prim}])
    assert reason is not None and "primitive" in reason
    obj = ObjectEntry(
        location="app/o", serializer="pickle", obj_type="T", replicated=False
    )
    assert _degrade_eligible([{"app/o": obj}]) is not None


# ------------------------------------------------ chaos spec extensions


def test_fault_spec_rank_and_wedge_parse():
    from tpusnap.faults import FaultPlan

    plan = FaultPlan.from_spec("rank=1,crash_after_op=write:2,wedge=read:3")
    assert plan.rank == 1
    assert plan.crash_after_op == ("write", 2)
    assert plan.wedge == ("read", 3)
    assert FaultPlan.from_spec("wedge=write:*").wedge == ("write", 0)
    assert FaultPlan.from_spec("wedge=write").wedge == ("write", 0)


def test_rank_filter_neutralizes_plan_on_other_ranks(monkeypatch):
    import tpusnap.faults as faults_mod
    from tpusnap.faults import FaultInjectionStoragePlugin, FaultPlan

    monkeypatch.setattr(faults_mod, "_process_rank", lambda: 0)
    inner = object.__new__(FaultInjectionStoragePlugin)  # placeholder inner

    plugin = FaultInjectionStoragePlugin.__new__(FaultInjectionStoragePlugin)
    FaultInjectionStoragePlugin.__init__(
        plugin, inner, FaultPlan(rank=1, transient_per_op=3, torn_writes=True)
    )
    # Mismatched rank: the plan is inert (no transients, no tears).
    assert plugin.plan.transient_per_op == 0
    assert plugin.plan.torn_writes is False
    # Matching rank keeps the faults.
    monkeypatch.setattr(faults_mod, "_process_rank", lambda: 1)
    plugin2 = FaultInjectionStoragePlugin.__new__(FaultInjectionStoragePlugin)
    FaultInjectionStoragePlugin.__init__(
        plugin2, inner, FaultPlan(rank=1, transient_per_op=3)
    )
    assert plugin2.plan.transient_per_op == 3


def test_wedge_sigstops_on_the_planned_attempt(monkeypatch):
    from tpusnap.faults import FaultInjectionStoragePlugin, FaultPlan

    sent = []
    monkeypatch.setattr(
        os, "kill", lambda pid, sig: sent.append((pid, sig))
    )
    plugin = FaultInjectionStoragePlugin.__new__(FaultInjectionStoragePlugin)
    FaultInjectionStoragePlugin.__init__(
        plugin, object(), FaultPlan(wedge=("write", 2))
    )
    plugin._check_wedge("write")
    assert sent == []
    plugin._check_wedge("read")  # other kinds don't advance the counter
    assert sent == []
    plugin._check_wedge("write")
    assert sent == [(os.getpid(), signal.SIGSTOP)]


# ------------------------------------------- post-mortem verdict folding


def test_postmortem_verdict_folds_dead_ranks():
    from tpusnap.flight import postmortem_verdict

    logs = {
        0: {
            "meta": {"world_size": 3, "take_id": "x"},
            "events": [
                {"k": "rank_dead", "t": 1.0, "rank": 2},
                {"k": "abort", "t": 1.1},
            ],
        },
        1: {"meta": {"world_size": 3}, "events": []},
    }
    v = postmortem_verdict("/p", "torn", logs)
    assert v["dead_ranks"] == [2]
    assert v["ranks"][0]["dead_ranks_seen"] == [2]
    assert v["missing_ranks"] == [2]


def test_stall_episode_carries_dead_ranks():
    from tpusnap import telemetry
    from tpusnap.progress import ProgressMonitor

    rec = telemetry.TakeTelemetry(rank=0, enabled=True)
    tok = rec.op_enter("storage.write")
    clock = FakeClock()
    mon = ProgressMonitor(
        rec, 0, 2, "take", thread=False, clock=clock,
        stall_deadline_s=5.0, interval_s=0.5,
    )
    mon.set_liveness_probe(lambda: [1])
    mon.tick(now=clock.t)
    clock.advance(10.0)
    mon.tick(now=clock.t)
    rec.op_exit(tok)
    # The heartbeat record surfaces the dead peer too.
    payload = mon._record(clock.t, rec.live_snapshot())
    assert payload["dead_ranks"] == [1]
    rec.finalize()  # stop the recorder's RSS-sampler thread


def test_watch_table_flags_dead_peers():
    from tpusnap.progress import render_watch_table

    out = render_watch_table(
        [
            {
                "rank": 0,
                "state": "running",
                "phase": "stage",
                "op": "x",
                "percent": 10.0,
                "mbps": 1.0,
                "beat_age_s": 0.1,
                "ts": 100.0,
                "dead_ranks": [1],
            }
        ],
        committed=False,
        stall_flag_s=15.0,
        now=100.0,
    )
    assert "PEER DEAD [1]" in out


# ------------------------------------------------- multi-process layer


_TTL = 2.0
_LIVENESS_ENV = {
    "TPUSNAP_LIVENESS_TTL_S": str(_TTL),
    "TPUSNAP_HEARTBEAT_INTERVAL_S": "0.1",
    "TPUSNAP_DISABLE_BATCHING": "1",
    "TPUSNAP_HISTORY": "0",
}


def _state(nbytes_per_arr=1 << 18, n=6, seed=7):
    rng = np.random.default_rng(seed)
    return {
        f"w{i}": rng.standard_normal(nbytes_per_arr // 8)
        for i in range(n)
    }


def _world_kill_one_rank(snap_dir, window):
    """Rank 1 SIGKILLs itself inside ``window``; rank 0 must raise
    RankFailedError naming it within 3x the lease TTL of the kill."""
    import jax  # noqa: F401  (world is initialized)

    from tpusnap import RankFailedError, Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    marker = os.path.join(snap_dir, f"killed_at.{window}")

    def mark_and_die():
        with open(marker, "w") as f:
            f.write(repr(time.time()))
            f.flush()
            os.fsync(f.fileno())
        os.kill(os.getpid(), signal.SIGKILL)

    if comm.rank == 1:
        if window == "stage":
            from tpusnap.io_preparers import array as arr_mod

            orig = arr_mod.ArrayBufferStager._stage_blocking
            fired = [0]

            def hooked(self):
                fired[0] += 1
                if fired[0] == 1:
                    mark_and_die()
                return orig(self)

            arr_mod.ArrayBufferStager._stage_blocking = hooked
        elif window == "write":
            import tpusnap.storage_plugins.fs as fs_mod

            orig_write = fs_mod.FSStoragePlugin.write
            fired = [0]

            async def hooked_write(self, write_io):
                await orig_write(self, write_io)
                if not write_io.path.startswith(".tpusnap"):
                    fired[0] += 1
                    if fired[0] == 1:
                        mark_and_die()

            fs_mod.FSStoragePlugin.write = hooked_write
        elif window == "commit_barrier":
            import tpusnap.comm as comm_mod

            orig_barrier = comm_mod.JaxCoordinationComm._polling_barrier

            def hooked_barrier(self, seq):
                # Collective sequence of a 2-rank replicated take: G1
                # gather (seq 1) + barrier (2), G2 gather (3) + barrier
                # (4), then the commit barrier (5) — die INSIDE it.
                # (The polling mode only engages once the abort watcher
                # is armed after G1, so this hook first sees seq 4.)
                if seq >= 5:
                    mark_and_die()
                return orig_barrier(self, seq)

            comm_mod.JaxCoordinationComm._polling_barrier = hooked_barrier
        else:
            raise AssertionError(window)

    state = {
        "m": StateDict(
            **{
                k: v.astype(np.float32)
                for k, v in _state(n=4).items()
            }
        )
    }
    t0 = time.time()
    try:
        Snapshot.take(snap_dir, state, replicated=["**"])
    except RankFailedError as e:
        assert e.ranks == [1], e.ranks
        detect = time.time()
        killed_at = None
        try:
            with open(marker) as f:
                killed_at = float(f.read())
        except OSError:
            pass
        dt = detect - (killed_at if killed_at is not None else t0)
        print(f"RANKFAILED window={window} dt={dt:.2f}", flush=True)
        ttl = float(os.environ["TPUSNAP_LIVENESS_TTL_S"])  # tpusnap: waive=TPS001 test plumbing
        assert dt <= 3.0 * ttl, (
            f"detection took {dt:.2f}s > 3x TTL ({3 * ttl:.1f}s)"
        )
        # Skip jax.distributed's shutdown rendezvous: with a SIGKILLed
        # peer it parks until ITS timeout — the exact hang class this
        # test exists to eliminate from the take path.
        os._exit(0)
    else:
        raise AssertionError("rank 0 did not observe the rank failure")


@pytest.mark.distributed
@pytest.mark.parametrize("window", ["stage", "write", "commit_barrier"])
def test_rank_death_fails_fast_and_names_the_rank(tmp_path, window):
    """ISSUE 15 acceptance: a SIGKILLed peer is detected in <= 3x TTL
    (seconds), not the 600/1800 s barrier timeouts."""
    from tpusnap.test_utils import run_subprocess_world

    snap = str(tmp_path / f"snap_{window}")
    os.makedirs(snap, exist_ok=True)
    with pytest.raises(RuntimeError) as ei:
        run_subprocess_world(
            _world_kill_one_rank,
            world_size=2,
            args=[snap, window],
            extra_env=_LIVENESS_ENV,
            timeout=120,
        )
    logs = str(ei.value)
    # Rank 1 died by SIGKILL (the harness reports it failed); rank 0
    # printed the fast-detection proof before exiting cleanly.
    m = re.search(rf"RANKFAILED window={window} dt=([0-9.]+)", logs)
    assert m, f"rank 0 never printed detection proof:\n{logs[-3000:]}"
    assert float(m.group(1)) <= 3.0 * _TTL


def _world_degraded_replicated_take(snap_dir):
    """Degrade mode: rank 1 dies mid-write of a fully-replicated take;
    rank 0 completes it, restores bit-exact, and the metadata records
    the adoption."""
    import jax  # noqa: F401

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    arrays = {
        k: v.astype(np.float32) for k, v in _state(n=6, seed=11).items()
    }
    if comm.rank == 1:
        import tpusnap.storage_plugins.fs as fs_mod

        orig_write = fs_mod.FSStoragePlugin.write
        fired = [0]

        async def hooked_write(self, write_io):
            await orig_write(self, write_io)
            if not write_io.path.startswith(".tpusnap"):
                fired[0] += 1
                if fired[0] == 2:
                    os.kill(os.getpid(), signal.SIGKILL)

        fs_mod.FSStoragePlugin.write = hooked_write

    state = {"m": StateDict(step=42, **arrays)}
    snap = Snapshot.take(snap_dir, state, replicated=["**"])
    assert comm.rank == 0  # rank 1 never gets here

    deg = (snap.metadata.extras or {}).get("degraded")
    assert deg and deg["dead_ranks"] == [1], deg
    assert deg["live_ranks"] == [0]
    # Bit-exact restore of every leaf, from the degraded snapshot.
    target = {
        "m": StateDict(
            step=0, **{k: np.zeros_like(v) for k, v in arrays.items()}
        )
    }
    Snapshot(snap_dir).restore(target)
    assert target["m"]["step"] == 42
    for k, v in arrays.items():
        assert np.array_equal(target["m"][k], v), k
    # Integrity: every referenced byte re-reads clean.
    rep = verify_snapshot(snap_dir)
    assert rep.clean and not rep.corrupt, rep
    from tpusnap.lifecycle import fsck_snapshot

    fr = fsck_snapshot(snap_dir)
    assert fr.state == "committed", fr.summary()
    assert "DEGRADED" in fr.summary()
    print("DEGRADED-OK", flush=True)
    os._exit(0)  # skip the shutdown rendezvous with the dead peer


@pytest.mark.distributed
def test_degraded_commit_completes_replicated_take(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    snap = str(tmp_path / "snap_degraded")
    env = dict(_LIVENESS_ENV, TPUSNAP_RANK_FAILURE="degrade")
    with pytest.raises(RuntimeError) as ei:
        run_subprocess_world(
            _world_degraded_replicated_take,
            world_size=2,
            args=[snap],
            extra_env=env,
            timeout=120,
        )
    logs = str(ei.value)
    assert "DEGRADED-OK" in logs, logs[-3000:]
    assert "Ranks [1] failed" in logs  # ONLY the SIGKILLed rank failed


def _world_sharded_death_aborts_torn(snap_dir):
    """Degrade mode with SHARDED state: the dead rank held unique
    shards — the survivors must refuse to degrade and abort to a torn
    state (salvageable, dead rank named by the black box)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from tpusnap import RankFailedError, Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    devices = np.array(jax.devices()).reshape(-1)
    mesh = Mesh(devices, ("x",))
    sharding = NamedSharding(mesh, PartitionSpec("x"))
    n = len(devices) * 8
    full = np.arange(n * 16, dtype=np.float32).reshape(n, 16)
    # Per-process local shards of a genuinely non-fully-addressable
    # global array (device_put of the full value would need real
    # multi-process computation; the callback path does not).
    sharded = jax.make_array_from_callback(
        full.shape, sharding, lambda idx: full[idx]
    )
    arrays = {
        k: v.astype(np.float32) for k, v in _state(n=4, seed=3).items()
    }
    if comm.rank == 1:
        import tpusnap.storage_plugins.fs as fs_mod

        orig_write = fs_mod.FSStoragePlugin.write
        fired = [0]

        async def hooked_write(self, write_io):
            await orig_write(self, write_io)
            if not write_io.path.startswith(".tpusnap"):
                fired[0] += 1
                if fired[0] == 2:
                    os.kill(os.getpid(), signal.SIGKILL)

        fs_mod.FSStoragePlugin.write = hooked_write

    state = {"m": StateDict(s=sharded, **arrays)}
    try:
        Snapshot.take(snap_dir, state, replicated=["m/w*"])
    except RankFailedError as e:
        assert e.ranks == [1]
        assert "degrade refused" in str(e) or "failed during take" in str(e)
        print("SHARDED-ABORT-OK", flush=True)
        os._exit(0)  # skip the shutdown rendezvous with the dead peer
    else:
        raise AssertionError("sharded-state death must not commit")


@pytest.mark.distributed
def test_sharded_death_aborts_torn_named_and_salvageable(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    snap = str(tmp_path / "snap_sharded")
    env = dict(_LIVENESS_ENV, TPUSNAP_RANK_FAILURE="degrade")
    with pytest.raises(RuntimeError) as ei:
        run_subprocess_world(
            _world_sharded_death_aborts_torn,
            world_size=2,
            args=[snap],
            extra_env=env,
            timeout=120,
        )
    logs = str(ei.value)
    assert "SHARDED-ABORT-OK" in logs, logs[-3000:]

    # The path is TORN (survivor kept its blobs + journal as salvage
    # substrate) and both verdicts name the dead rank.
    from tpusnap.lifecycle import fsck_snapshot

    report = fsck_snapshot(snap)
    assert report.state == "torn", report.summary()
    assert report.salvage_bytes_present > 0

    from tpusnap.flight import load_flight_logs, postmortem_verdict

    flogs = load_flight_logs(snap, files=report.files)
    verdict = postmortem_verdict(snap, report.state, flogs)
    assert 1 in verdict["dead_ranks"], verdict

    # Retake over the torn path (a fresh single-process job — the
    # glob-replicated arrays land at the same rank-agnostic locations
    # with the same bytes): the dual-hash rule must salvage >= 50% of
    # the survivor's completed bytes.
    from tpusnap import Snapshot, StateDict, telemetry

    arrays = {
        k: v.astype(np.float32) for k, v in _state(n=4, seed=3).items()
    }
    from tpusnap.knobs import override_batching_disabled

    before = telemetry.counter_value("salvage.bytes_salvaged")
    with override_batching_disabled(True):  # match the torn take's layout
        snap2 = Snapshot.take(
            snap, {"m": StateDict(**arrays)}, replicated=["m/w*"]
        )
    salvaged = telemetry.counter_value("salvage.bytes_salvaged") - before
    assert salvaged >= 0.5 * report.salvage_bytes_present, (
        salvaged,
        report.salvage_bytes_present,
    )
    target = {
        "m": StateDict(**{k: np.zeros_like(v) for k, v in arrays.items()})
    }
    snap2.restore(target)
    for k, v in arrays.items():
        assert np.array_equal(target["m"][k], v), k
