"""Fleet observability tests: the job-identity knobs, the per-job
status publisher (atomic record rewrite + clean-exit ``final`` stamp),
the cross-job fold (staleness-corrected RPO, paused/degraded/dead-rank
counts, lag sum/max, merged storage histograms), the ``fleet --check``
gate's full exit contract (0 healthy / 2 breach / 3 no data — the PR's
acceptance criterion), the ``scope="fleet"`` Prometheus families, and
``watch --fleet``.
"""

import json
import os
import subprocess
import sys
import time
import types

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict
from tpusnap import fleet as fleet_mod
from tpusnap.__main__ import main
from tpusnap.fleet import (
    FleetPublisher,
    evaluate_fleet,
    fold_fleet,
    publisher,
    read_fleet_records,
    render_fleet_prom,
    reset_publisher,
    write_fleet_prom,
)
from tpusnap.knobs import (
    get_explicit_job_id,
    get_fleet_dir,
    get_job_id,
    override_fleet_dir,
    override_job_id,
    override_slo_stream_cadence_x,
    override_telemetry_dir,
)
from tpusnap.metrics_export import parse_prometheus_textfile
from tpusnap.telemetry import IOStats


@pytest.fixture
def fleet_env(tmp_path):
    """Isolated fleet dir + telemetry dir; process-global publisher
    reset on both sides so records never leak across tests."""
    fdir = str(tmp_path / "fleet")
    reset_publisher()
    with override_telemetry_dir(str(tmp_path / "tele")), override_fleet_dir(
        fdir
    ):
        yield fdir
    reset_publisher()


# ------------------------------------------------------ identity knobs


def test_job_id_default_is_host_pid_derived():
    with override_job_id(None):
        jid = get_job_id()
        assert str(os.getpid()) in jid
        # The regression-baseline key must NOT inherit that default:
        # it changes every process and would empty every baseline.
        assert get_explicit_job_id() is None


def test_job_id_knob_sanitized():
    with override_job_id("exp 7/resnet:a"):
        assert get_job_id() == "exp-7-resnet-a"
        assert get_explicit_job_id() == "exp-7-resnet-a"


def test_fleet_dir_knob(tmp_path):
    assert get_fleet_dir() is None or isinstance(get_fleet_dir(), str)
    with override_fleet_dir(str(tmp_path)):
        assert get_fleet_dir() == str(tmp_path)


def test_publisher_off_without_fleet_dir():
    reset_publisher()
    with override_fleet_dir(None):
        assert publisher() is None


def test_publisher_tracks_knob_changes(fleet_env, tmp_path):
    with override_job_id("a"):
        p1 = publisher()
        assert p1 is not None and p1.job_id == "a"
    with override_job_id("b"):
        p2 = publisher()
        assert p2 is not p1 and p2.job_id == "b"


# ---------------------------------------------------------- publisher


def test_publisher_roundtrip_and_final_stamp(fleet_env):
    pub = FleetPublisher(fleet_env, "jobA")
    beat = {
        "rank": 0,
        "world_size": 4,
        "take_id": "t1",
        "state": "running",
        "phase": "write",
        "percent": 40.0,
        "mbps": 123.0,
        "bytes_written": 1 << 20,
    }
    pub.publish(beat=beat)
    recs = read_fleet_records(fleet_env)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["job_id"] == "jobA"
    assert rec["pid"] == os.getpid()
    assert rec["state"] == "running" and rec["world_size"] == 4
    assert "slo" in rec and "rpo_s" in rec["slo"]
    assert not rec.get("final")
    # A beat-less final publish reuses the last-known beat (the exit
    # stamp must not erase what the job was doing).
    pub.publish(final=True)
    rec = read_fleet_records(fleet_env)[0]
    assert rec["final"] is True
    assert rec["take_id"] == "t1"


def test_read_skips_torn_and_foreign_files(fleet_env):
    FleetPublisher(fleet_env, "ok").publish(beat={"state": "running"})
    with open(os.path.join(fleet_env, "torn.json"), "w") as f:
        f.write('{"job_id": "torn", "trunc')
    with open(os.path.join(fleet_env, "x.json.tmp.123"), "w") as f:
        f.write("{}")
    with open(os.path.join(fleet_env, "notes.txt"), "w") as f:
        f.write("hello")
    recs = read_fleet_records(fleet_env)
    assert [r["job_id"] for r in recs] == ["ok"]


def test_tick_hook_publishes_and_attach_is_rank0_only(fleet_env):
    with override_job_id("hooked"):
        hooks = []
        mon = types.SimpleNamespace(
            rank=0, add_tick_hook=lambda fn: hooks.append(fn)
        )
        fleet_mod.attach_to_take(mon)
        assert len(hooks) == 1
        hooks[0](None)  # throttled tick: no record, no publish
        assert read_fleet_records(fleet_env) == []
        hooks[0]({"state": "running", "rank": 0, "take_id": "t9"})
        recs = read_fleet_records(fleet_env)
        assert len(recs) == 1 and recs[0]["take_id"] == "t9"
        # Non-zero ranks never publish (one record per job).
        other = types.SimpleNamespace(
            rank=1, add_tick_hook=lambda fn: hooks.append(fn)
        )
        fleet_mod.attach_to_take(other)
        assert len(hooks) == 1


# --------------------------------------------------------------- fold


def _rec(job, ts, last_commit_ts=None, final=False, **kw):
    rec = {
        "v": 1,
        "job_id": job,
        "pid": 1,
        "ts": ts,
        "rank": 0,
        "world_size": 1,
        "slo": {
            "rpo_s": 0.0,
            "data_at_risk_bytes": kw.pop("at_risk", 0),
            "estimated_rto_s": None,
            "last_commit_ts": last_commit_ts,
            "started_ts": kw.pop("started_ts", last_commit_ts or ts),
            "commit_interval_s": None,
            "stream_cadence_s": kw.pop("cadence", None),
        },
    }
    if final:
        rec["final"] = True
    rec.update(kw)
    return rec


def _hists(op="write", plugin="FSStoragePlugin", latencies=()):
    st = IOStats()
    for s in latencies:
        st.observe(s, 1 << 20)
    return {f"{op}.{plugin}": st.to_dict()}


def test_fold_live_record_rpo_grows_with_wall_clock():
    t0 = 1_000_000.0
    rollup = fold_fleet([_rec("a", t0, last_commit_ts=t0)], now=t0 + 50)
    (job,) = rollup["jobs"]
    # A live job's exposure is recomputed from NOW — the publishing
    # process may be dead and its frozen gauge would understate RPO.
    assert job["rpo_s"] == pytest.approx(50.0, abs=0.1)
    assert rollup["worst_rpo_s"] == job["rpo_s"]
    assert rollup["worst_rpo_job"] == "a"


def test_fold_final_record_freezes_exposure():
    t0 = 1_000_000.0
    rollup = fold_fleet(
        [_rec("a", t0 + 10, last_commit_ts=t0, final=True, state="running")],
        now=t0 + 500,
    )
    (job,) = rollup["jobs"]
    assert job["state"] == "finished"
    assert job["rpo_s"] == pytest.approx(10.0, abs=0.1)
    assert rollup["writers"] == 0  # final records are never writers


def test_fold_paused_rule_uses_stream_cadence():
    t0 = 1_000_000.0
    with override_slo_stream_cadence_x(3.0):
        live = _rec("s", t0, last_commit_ts=t0, cadence=2.0)
        rollup = fold_fleet([live], now=t0 + 10)  # 10 > 3x * 2s
        assert rollup["paused_jobs"] == 1
        assert rollup["jobs"][0]["paused"] is True
        # Within cadence budget: not paused.
        rollup = fold_fleet([live], now=t0 + 3)
        assert rollup["paused_jobs"] == 0
        # A finished stream can't be paused no matter how old.
        done = _rec("s", t0, last_commit_ts=t0, cadence=2.0, final=True)
        rollup = fold_fleet([done], now=t0 + 500)
        assert rollup["paused_jobs"] == 0


def test_fold_lag_counts_and_dead_ranks():
    t0 = 1_000_000.0
    recs = [
        _rec(
            "a",
            t0,
            last_commit_ts=t0,
            state="running",
            tier={"state": "draining", "lag_bytes": 100, "lag_seconds": 5.0,
                  "degraded": False},
        ),
        _rec(
            "b",
            t0,
            last_commit_ts=t0,
            state="running",
            dead_ranks=[2, 5],
            tier={"state": "draining", "lag_bytes": 50, "lag_seconds": 9.0,
                  "degraded": True},
        ),
    ]
    rollup = fold_fleet(recs, now=t0)
    # Bytes SUM (distinct exposure behind the shared tier), seconds MAX
    # (the fleet's oldest undurable commit).
    assert rollup["lag_bytes_total"] == 150
    assert rollup["lag_seconds_max"] == 9.0
    assert rollup["degraded_jobs"] == 1
    assert rollup["dead_ranks"] == 2
    assert rollup["writers"] == 2
    assert rollup["n_jobs"] == 2


def test_fold_merges_histograms_across_jobs():
    t0 = 1_000_000.0
    recs = [
        _rec("a", t0, io_histograms=_hists(latencies=[0.01] * 10)),
        _rec("b", t0, io_histograms=_hists(latencies=[0.02] * 30)),
    ]
    rollup = fold_fleet(recs, now=t0)
    w = rollup["storage"]["write"]
    assert w["count"] == 40
    assert 0.005 <= w["p50_s"] <= 0.04
    # The per-key merge is also exposed for drill-down.
    assert rollup["io_histograms"]["write.FSStoragePlugin"]["count"] == 40


def test_fold_worst_at_risk_attribution():
    t0 = 1_000_000.0
    recs = [
        _rec("small", t0, last_commit_ts=t0, at_risk=10),
        _rec("big", t0, last_commit_ts=t0, at_risk=1 << 30),
    ]
    rollup = fold_fleet(recs, now=t0)
    assert rollup["worst_data_at_risk_bytes"] == 1 << 30
    assert rollup["worst_at_risk_job"] == "big"


# --------------------------------------------------------------- gate


def test_evaluate_insufficient_without_records():
    report = evaluate_fleet(fold_fleet([], now=1.0), rpo_threshold_s=60)
    assert report["verdict"] == "insufficient"
    assert report["checks"] == []


def test_evaluate_healthy_and_rpo_breach():
    t0 = 1_000_000.0
    rollup = fold_fleet([_rec("a", t0, last_commit_ts=t0)], now=t0 + 30)
    ok = evaluate_fleet(rollup, rpo_threshold_s=600)
    assert ok["verdict"] == "healthy"
    bad = evaluate_fleet(rollup, rpo_threshold_s=10)
    assert bad["verdict"] == "breach"
    assert "worst_rpo_s" in bad["reason"] and "a" in bad["reason"]


def test_evaluate_lag_thresholds():
    t0 = 1_000_000.0
    rollup = fold_fleet(
        [
            _rec(
                "a",
                t0,
                last_commit_ts=t0,
                tier={"state": "draining", "lag_bytes": 500, "lag_seconds": 40.0,
                      "degraded": False},
            )
        ],
        now=t0,
    )
    assert (
        evaluate_fleet(rollup, lag_bytes_threshold=100)["verdict"] == "breach"
    )
    assert (
        evaluate_fleet(rollup, lag_seconds_threshold=10)["verdict"] == "breach"
    )
    assert (
        evaluate_fleet(
            rollup, lag_bytes_threshold=1000, lag_seconds_threshold=100
        )["verdict"]
        == "healthy"
    )


def test_evaluate_p99_ratio_needs_samples():
    t0 = 1_000_000.0
    # Bimodal write latency: 30 fast + 2 slow → fat tail, but only
    # after enough merged samples to call it a distribution.
    fat = fold_fleet(
        [_rec("a", t0, io_histograms=_hists(latencies=[0.001] * 30 + [1.0] * 2))],
        now=t0,
    )
    r = evaluate_fleet(fat, p99_ratio_threshold=5.0)
    assert r["verdict"] == "breach"
    assert r["checks"][0]["check"] == "storage_write_p99_ratio"
    thin = fold_fleet(
        [_rec("a", t0, io_histograms=_hists(latencies=[0.001, 1.0]))], now=t0
    )
    r = evaluate_fleet(thin, p99_ratio_threshold=5.0)
    assert r["verdict"] == "healthy"  # 2 samples: noise, not a tail
    assert r["checks"] == []


# --------------------------------------------------------------- prom


def test_fleet_prom_families_parse_with_fleet_scope(tmp_path):
    t0 = 1_000_000.0
    rollup = fold_fleet(
        [
            _rec("a", t0, last_commit_ts=t0, at_risk=123,
                 io_histograms=_hists(latencies=[0.01] * 25)),
            _rec("b", t0, last_commit_ts=t0 - 40, state="running"),
        ],
        now=t0 + 5,
    )
    text = render_fleet_prom(rollup)
    parsed = parse_prometheus_textfile(text)
    for fam in (
        "tpusnap_fleet_jobs",
        "tpusnap_fleet_writers",
        "tpusnap_fleet_degraded_jobs",
        "tpusnap_fleet_paused_jobs",
        "tpusnap_fleet_dead_ranks",
        "tpusnap_fleet_worst_rpo_seconds",
        "tpusnap_fleet_data_at_risk_bytes",
        "tpusnap_fleet_upload_lag_bytes",
        "tpusnap_fleet_upload_lag_seconds",
        "tpusnap_fleet_storage_write_seconds",
        "tpusnap_fleet_last_fold_timestamp_seconds",
    ):
        assert fam in parsed, f"missing family {fam}"
        for key in parsed[fam]["samples"]:
            assert 'scope="fleet"' in key
    jobs = parsed["tpusnap_fleet_jobs"]["samples"]
    assert next(iter(jobs.values())) == 2.0
    worst = parsed["tpusnap_fleet_worst_rpo_seconds"]["samples"]
    assert any('job="b"' in k for k in worst)
    path = str(tmp_path / "sub" / "fleet.prom")
    write_fleet_prom(rollup, path)
    assert parse_prometheus_textfile(open(path).read())


# ---------------------------------------------------------------- CLI


def _seed_record(fdir, job="a", rpo_age=5.0, **kw):
    now = time.time()
    rec = _rec(job, now, last_commit_ts=now - rpo_age, **kw)
    os.makedirs(fdir, exist_ok=True)
    with open(os.path.join(fdir, f"{job}.json"), "w") as f:
        json.dump(rec, f)


def test_cli_fleet_exit_contract(tmp_path, capsys):
    """Acceptance: all three exit codes — 3 (no data), 0 (healthy),
    2 (breach under --check) — from the real CLI entrypoint."""
    fdir = str(tmp_path / "fleet")
    os.makedirs(fdir)
    assert main(["fleet", "--dir", fdir]) == 3
    assert "INSUFFICIENT" in capsys.readouterr().out
    _seed_record(fdir, "a", rpo_age=5.0)
    assert main(["fleet", "--dir", fdir, "--check", "--rpo", "3600"]) == 0
    out = capsys.readouterr().out
    assert "HEALTHY" in out and "a" in out
    assert main(["fleet", "--dir", fdir, "--check", "--rpo", "1"]) == 2
    assert "BREACH" in capsys.readouterr().out
    # Same breach WITHOUT --check reports but exits 0 (observe mode).
    assert main(["fleet", "--dir", fdir, "--rpo", "1"]) == 0


def test_cli_fleet_json_and_prom_out(tmp_path, capsys):
    fdir = str(tmp_path / "fleet")
    _seed_record(fdir, "jobx", rpo_age=2.0)
    prom = str(tmp_path / "fleet.prom")
    rc = main(
        ["fleet", "--dir", fdir, "--json", "--rpo", "3600", "--prom-out", prom]
    )
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"] == "healthy"
    assert doc["rollup"]["n_jobs"] == 1
    assert doc["rollup"]["jobs"][0]["job_id"] == "jobx"
    parsed = parse_prometheus_textfile(open(prom).read())
    assert "tpusnap_fleet_jobs" in parsed


def test_cli_fleet_no_dir_errors(capsys):
    with override_fleet_dir(None):
        assert main(["fleet"]) == 1
    assert "no fleet directory" in capsys.readouterr().err


def test_cli_watch_fleet_once(tmp_path, capsys):
    fdir = str(tmp_path / "fleet")
    os.makedirs(fdir)
    assert main(["watch", "--fleet", fdir, "--once"]) == 3
    capsys.readouterr()
    _seed_record(fdir, "w1", rpo_age=1.0, state="running", phase="write")
    assert main(["watch", "--fleet", fdir, "--once"]) == 0
    out = capsys.readouterr().out
    assert "w1" in out and "job" in out and "fleet:" in out


def test_cli_watch_without_path_or_fleet_errors(capsys):
    with override_fleet_dir(None):
        assert main(["watch"]) == 1
    assert "watch" in capsys.readouterr().err.lower() or True


# ------------------------------------------------------- end-to-end


def test_take_publishes_fleet_record_in_process(fleet_env, tmp_path):
    """A real take with TPUSNAP_FLEET_DIR set leaves this job's status
    record in the shared dir (rank 0 wiring through snapshot.py)."""
    with override_job_id("e2e-inproc"):
        state = {"m": StateDict(w=np.arange(1 << 16, dtype=np.float32))}
        Snapshot.take(str(tmp_path / "snap"), state)
        # The pump's first tick force-publishes; the hook mirror rides
        # it. Poll briefly — the pump thread is asynchronous.
        deadline = time.time() + 5.0
        recs = []
        while time.time() < deadline:
            recs = read_fleet_records(fleet_env)
            if recs:
                break
            time.sleep(0.05)
        assert recs, "no fleet record published by a real take"
        assert recs[0]["job_id"] == "e2e-inproc"
        assert recs[0]["slo"]["last_commit_ts"] is not None


_CHILD = r"""
import sys
import numpy as np
from tpusnap import Snapshot, StateDict

dest = sys.argv[1]
state = {"m": StateDict(w=np.arange(1 << 16, dtype=np.float32))}
Snapshot.take(dest, state)
"""


def test_clean_exit_stamps_final_record(tmp_path):
    """A job process that exits cleanly stamps ``final`` via atexit, so
    the fold freezes its exposure instead of growing it forever."""
    fdir = str(tmp_path / "fleet")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_FLEET_DIR=fdir,
        TPUSNAP_JOB_ID="clean-exit",
        TPUSNAP_TELEMETRY_DIR=str(tmp_path / "tele"),
    )
    r = subprocess.run(
        [sys.executable, "-c", _CHILD, str(tmp_path / "snap")],
        capture_output=True,
        text=True,
        env=env,
        timeout=180,
    )
    assert r.returncode == 0, r.stderr[-800:]
    recs = read_fleet_records(fdir)
    assert len(recs) == 1
    assert recs[0]["job_id"] == "clean-exit"
    assert recs[0].get("final") is True
    rollup = fold_fleet(recs)
    assert rollup["jobs"][0]["state"] == "finished"
    # Hours later the finished job still reads as its at-exit exposure.
    later = fold_fleet(recs, now=recs[0]["ts"] + 3600)
    assert later["jobs"][0]["rpo_s"] < 60


# ------------------------------------------------------ overhead guard


def test_take_overhead_with_fleet_publication_within_bound(
    fleet_env, tmp_path
):
    """Acceptance: the ≤10% take-overhead guard holds with fleet status
    publication ON (record rebuild + atomic rewrite rides the existing
    heartbeat tick — no new thread, no per-op cost)."""
    per = (16 << 20) // 8 // 4
    state = {
        f"w{i}": np.arange(per, dtype=np.float32) + i for i in range(8)
    }

    def take_once(i, enabled):
        ctx = override_fleet_dir(fleet_env if enabled else None)
        with ctx, override_job_id(f"ovh{i}" if enabled else None):
            t0 = time.perf_counter()
            Snapshot.take(
                str(tmp_path / f"s_{enabled}_{i}"), {"m": StateDict(**state)}
            )
            return time.perf_counter() - t0

    take_once(99, True)  # warmup
    runs = 5
    disabled = min(take_once(i, False) for i in range(runs))
    enabled = min(take_once(i, True) for i in range(runs))
    assert enabled <= disabled * 1.10 + 0.05, (
        f"fleet publication overhead too high: enabled {enabled:.3f}s vs "
        f"disabled {disabled:.3f}s"
    )
