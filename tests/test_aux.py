"""Tests for auxiliary subsystems: host offload (UVM analog), RSS
profiler, and the orbax drop-in trick."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusnap import (
    PytreeState,
    Snapshot,
    is_host_resident,
    measure_rss_deltas,
    supports_host_offload,
    to_device,
    to_host_offload,
)
from tpusnap.tricks.orbax import PyTreeCheckpointer


class TestHostOffload:
    def test_supports_on_cpu_backend(self):
        assert supports_host_offload()

    def test_roundtrip_and_predicates(self):
        x = jnp.arange(64, dtype=jnp.float32)
        # Whether a DEFAULT-placed array counts as host-resident depends
        # on the backend's default memory kind: TPU/GPU default to
        # device memory, but newer JAX CPU backends default to
        # unpinned_host — where reporting host residency is correct
        # (the save path rightly skips the DtoH staging copy there).
        try:
            default_kind = x.devices().pop().default_memory().kind
        except Exception:
            default_kind = "device"
        default_is_host = default_kind in ("pinned_host", "unpinned_host")
        assert is_host_resident(x) == default_is_host
        xh = to_host_offload(x, "unpinned_host")
        assert is_host_resident(xh)
        np.testing.assert_array_equal(np.asarray(xh), np.asarray(x))
        xd = to_device(xh)
        assert is_host_resident(xd) == default_is_host
        np.testing.assert_array_equal(np.asarray(xd), np.asarray(x))

    def test_numpy_is_host_resident(self):
        assert is_host_resident(np.zeros(4))

    def test_snapshot_of_host_offloaded_array(self, tmp_path):
        """The UVM-embedding scenario: host-resident state snapshots and
        restores like any other array (reference gpu_tests/test_torchrec
        UVM cases)."""
        x = to_host_offload(jnp.arange(1024, dtype=jnp.float32), "unpinned_host")
        Snapshot.take(str(tmp_path / "s"), {"m": PytreeState({"emb": x})})
        target = PytreeState({"emb": jnp.zeros(1024, jnp.float32)})
        Snapshot(str(tmp_path / "s")).restore({"m": target})
        np.testing.assert_array_equal(
            np.asarray(target.tree["emb"]), np.asarray(x)
        )


class TestRSSProfiler:
    def test_samples_collected(self):
        deltas = []
        with measure_rss_deltas(deltas, interval_sec=0.01):
            # ~72MB: above glibc's maximum dynamic mmap threshold
            # (32 MiB), so the buffer is always freshly mmapped and the
            # RSS delta is visible even late in a long suite — a 16MB
            # allocation can be served from a recycled arena with zero
            # RSS movement.
            buf = np.ones(9_000_000)
            time.sleep(0.05)
            del buf
        assert len(deltas) >= 2
        assert max(deltas) > 0


class TestOrbaxTrick:
    def test_save_restore_with_target(self, tmp_path):
        ckpt = PyTreeCheckpointer()
        tree = {"w": jnp.arange(16.0), "nested": {"b": np.ones((4, 4)), "n": 3}}
        ckpt.save(tmp_path / "ck", tree)
        target = jax.tree.map(
            lambda x: x * 0 if hasattr(x, "dtype") else 0, tree
        )
        out = ckpt.restore(tmp_path / "ck", target)
        assert jax.tree.structure(out) == jax.tree.structure(tree)
        for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(tree)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_without_target_rebuilds_structure(self, tmp_path):
        ckpt = PyTreeCheckpointer()
        tree = {"a": jnp.ones(3), "nested": {"b": 7}}
        ckpt.save(tmp_path / "ck", tree)
        out = ckpt.restore(tmp_path / "ck")
        assert set(out) == {"a", "nested"}
        assert out["nested"]["b"] == 7
        np.testing.assert_array_equal(np.asarray(out["a"]), np.ones(3))

    def test_force_overwrites(self, tmp_path):
        ckpt = PyTreeCheckpointer()
        ckpt.save(tmp_path / "ck", {"a": jnp.ones(3)})
        ckpt.save(tmp_path / "ck", {"a": jnp.zeros(3)}, force=True)
        out = ckpt.restore(tmp_path / "ck", {"a": jnp.ones(3)})
        np.testing.assert_array_equal(np.asarray(out["a"]), np.zeros(3))

    def test_async_save(self, tmp_path):
        ckpt = PyTreeCheckpointer()
        pending = ckpt.async_save(tmp_path / "ck", {"a": jnp.arange(8.0)})
        snapshot = pending.wait()
        out = ckpt.restore(snapshot.path, {"a": jnp.zeros(8)})
        np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(8.0))


class TestAdviseHugepages:
    """advise_hugepages is best-effort: buffers stay fully usable whether
    or not the host supports anonymous THP."""

    def test_advised_buffers_usable(self):
        import numpy as np

        from tpusnap import _native

        big = _native.aligned_empty(8 << 20)  # above the 4 MiB threshold
        np.asarray(big)[:] = 7
        assert (np.asarray(big) == 7).all()
        small = _native.aligned_empty(1024)  # below: no-op path
        np.asarray(small)[:] = 1
        assert (np.asarray(small) == 1).all()

    def test_advise_arbitrary_arrays(self):
        import numpy as np

        from tpusnap import _native

        arr = np.random.default_rng(0).standard_normal(1 << 21)
        before = arr.copy()
        _native.advise_hugepages(arr)  # must not perturb contents
        assert (arr == before).all()
        _native.advise_hugepages(np.empty(0, np.uint8))  # empty: no-op
        # dtypes without buffer protocol (memoryview() raises on these)
        import ml_dtypes

        bf16 = np.ones(1 << 21, dtype=ml_dtypes.bfloat16)
        _native.advise_hugepages(bf16)
        assert (bf16 == 1).all()


def test_orbax_trick_incremental(tmp_path):
    import numpy as np

    from tpusnap import verify_snapshot
    from tpusnap.tricks.orbax import PyTreeCheckpointer

    ckpt = PyTreeCheckpointer()
    tree = {"w": np.arange(4096, dtype=np.float32), "step": 1}
    base, inc = tmp_path / "c0", tmp_path / "c1"
    ckpt.save(base, tree)
    ckpt.save(inc, tree, incremental_from=base)
    restored = ckpt.restore(inc)
    assert np.array_equal(restored["w"], tree["w"])
    assert verify_snapshot(str(inc)).clean
    pending = ckpt.async_save(tmp_path / "c2", tree, incremental_from=inc)
    pending.wait()
    assert verify_snapshot(str(tmp_path / "c2")).clean
