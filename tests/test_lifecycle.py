"""Unit tests for the crash-safe lifecycle layer (tpusnap.lifecycle):
take journal, fsck classification, gc, salvage records, and the
metadata self-checksum. Subprocess SIGKILL coverage of the same
machinery lives in test_crash_matrix.py."""

import json
import os

import numpy as np
import pytest

from tpusnap import (
    MetadataError,
    Snapshot,
    StateDict,
    fsck_snapshot,
    gc_snapshot,
    verify_snapshot,
)
from tpusnap.lifecycle import (
    JOURNAL_FNAME,
    TakeJournal,
    journal_rank_path,
)
from tpusnap.manifest import decode_metadata, encode_metadata


def _state(seed=0, n=4):
    return {
        f"w{i}": np.random.default_rng(seed * 100 + i)
        .standard_normal((64, 64))
        .astype(np.float32)
        for i in range(n)
    }


def _take(path, state):
    return Snapshot.take(str(path), {"app": StateDict(**state)})


# ----------------------------------------------------- metadata checksum


def test_metadata_roundtrip_and_external_json_contract(tmp_path):
    path = tmp_path / "snap"
    _take(path, _state())
    raw = open(path / ".snapshot_metadata", "rb").read()
    # External tooling contract: the file stays plain JSON, with the
    # self-checksum as its first key.
    d = json.loads(raw)
    assert next(iter(d)) == "self_checksum"
    md = decode_metadata(raw)
    assert md.world_size == 1
    # encode → decode is stable.
    assert decode_metadata(encode_metadata(md)).manifest.keys() == md.manifest.keys()


def test_metadata_bitrot_and_truncation_detected(tmp_path):
    path = tmp_path / "snap"
    _take(path, _state())
    raw = open(path / ".snapshot_metadata", "rb").read()
    # Flip one byte inside a value (keep it printable so JSON may still
    # parse — the checksum must catch it regardless).
    idx = raw.index(b'"world_size"') + 2
    flipped = raw[:idx] + bytes([raw[idx] ^ 0x01]) + raw[idx + 1 :]
    with pytest.raises(MetadataError, match="mismatch|torn|corrupt"):
        decode_metadata(flipped)
    with pytest.raises(MetadataError, match="torn|corrupt"):
        decode_metadata(raw[: len(raw) // 2])
    # A pre-field file (no self_checksum) parses unverified.
    legacy = json.dumps(
        {k: v for k, v in json.loads(raw).items() if k != "self_checksum"}
    ).encode()
    assert decode_metadata(legacy).world_size == 1


def test_metadata_wrong_json_shape_is_metadata_error(tmp_path):
    """Corruption that happens to parse as valid non-dict JSON must
    still surface as MetadataError, not an AttributeError traceback."""
    for payload in (b"[]", b"0", b'"x"'):
        with pytest.raises(MetadataError, match="torn|corrupt"):
            decode_metadata(payload)
    # fsck reports it as corrupt-metadata too.
    path = tmp_path / "snap"
    _take(path, _state())
    open(path / ".snapshot_metadata", "wb").write(b"[]")
    assert fsck_snapshot(str(path)).state == "corrupt-metadata"


def test_restore_of_corrupt_metadata_raises_clearly(tmp_path):
    path = tmp_path / "snap"
    _take(path, _state())
    mp = path / ".snapshot_metadata"
    raw = open(mp, "rb").read()
    open(mp, "wb").write(raw[: len(raw) - 40])
    with pytest.raises(RuntimeError, match="[Cc]orrupt|torn"):
        Snapshot(str(path)).metadata
    assert fsck_snapshot(str(path)).state == "corrupt-metadata"


# ------------------------------------------------------------ journal


def test_journal_written_during_take_and_cleared_after(tmp_path):
    path = tmp_path / "snap"
    seen = {}
    import tpusnap.storage_plugins.fs as fs_mod

    orig = fs_mod.FSStoragePlugin.write

    async def spying_write(self, write_io):
        if not write_io.path.startswith(".tpusnap/"):
            seen["journal_at_first_blob"] = os.path.exists(
                os.path.join(self.root, JOURNAL_FNAME)
            )
        await orig(self, write_io)

    fs_mod.FSStoragePlugin.write = spying_write
    try:
        _take(path, _state())
    finally:
        fs_mod.FSStoragePlugin.write = orig
    # The journal marker existed before the first blob write landed...
    assert seen.get("journal_at_first_blob") is True
    # ...and the commit cleared marker + records.
    assert not os.path.exists(path / JOURNAL_FNAME)
    assert not os.path.exists(path / journal_rank_path(0))
    assert fsck_snapshot(str(path)).state == "committed"


def test_journal_knob_disables_layer(tmp_path):
    from tpusnap.knobs import override_journal_disabled

    path = tmp_path / "snap"
    with override_journal_disabled(True):
        _take(path, _state())
    assert not os.path.exists(path / ".tpusnap/journal")
    assert fsck_snapshot(str(path)).state == "committed"


def test_aborted_take_clears_journal(tmp_path):
    """A FAILED (not SIGKILLed) take cleans its blobs AND its journal:
    the path reads as empty, not torn."""
    import tpusnap.storage_plugins.fs as fs_mod

    path = tmp_path / "snap"
    orig = fs_mod.FSStoragePlugin.write

    async def bad_write(self, write_io):
        raise ValueError("injected fatal (non-transient) failure")

    fs_mod.FSStoragePlugin.write = bad_write
    try:
        with pytest.raises(ValueError, match="injected fatal"):
            _take(path, _state())
    finally:
        fs_mod.FSStoragePlugin.write = orig
    report = fsck_snapshot(str(path))
    assert report.state == "empty", report.summary()
    # Path immediately reusable.
    _take(path, _state())
    assert fsck_snapshot(str(path)).state == "committed"


# --------------------------------------------------------------- fsck/gc


def test_fsck_foreign_and_torn_states(tmp_path):
    foreign = tmp_path / "foreign"
    foreign.mkdir()
    (foreign / "random.bin").write_bytes(b"hello")
    assert fsck_snapshot(str(foreign)).state == "foreign"

    torn = tmp_path / "torn"
    (torn / ".tpusnap/journal.d").mkdir(parents=True)
    (torn / JOURNAL_FNAME).write_text(
        TakeJournal(take_id="abcd" * 8, world_size=2, started_at=0.0).to_json()
    )
    (torn / journal_rank_path(0)).write_text(
        json.dumps({"0/app/w0": [16, "crc32c:00000001", "xxh64:" + "0" * 16]})
    )
    (torn / "0/app").mkdir(parents=True)
    (torn / "0/app/w0").write_bytes(b"x" * 16)
    report = fsck_snapshot(str(torn))
    assert report.state == "torn"
    assert report.salvage_records == 1
    assert report.salvage_bytes_present == 16


def test_record_file_without_marker_classifies_torn(tmp_path):
    """A gang-kill inside the pre-marker window leaves only a rank's
    eager record file; that alone must classify as torn, not foreign."""
    d = tmp_path / "premarker"
    (d / ".tpusnap/journal.d").mkdir(parents=True)
    (d / journal_rank_path(1)).write_text("{}")
    (d / "1").mkdir()
    (d / "1/blob").write_bytes(b"x" * 8)
    report = fsck_snapshot(str(d))
    assert report.state == "torn", report.summary()
    assert "pre-marker" in report.detail or "marker" in report.detail


def test_journal_tmp_debris_is_orphan(tmp_path):
    """`.tpusnap/*.tmp.<pid>` debris from a SIGKILLed atomic journal
    write must be fsck-visible and gc-reclaimable."""
    path = tmp_path / "snap"
    _take(path, _state())
    (path / ".tpusnap").mkdir(exist_ok=True)
    (path / ".tpusnap/journal.tmp.1234").write_bytes(b"{" + b"x" * 20)
    report = fsck_snapshot(str(path))
    assert report.state == "committed"
    assert ".tpusnap/journal.tmp.1234" in report.orphans, report.orphans
    g = gc_snapshot(str(path), dry_run=False)
    assert ".tpusnap/journal.tmp.1234" in g.reclaimed and not g.errors


def test_gc_refuses_torn_without_flag_then_reclaims(tmp_path):
    torn = tmp_path / "torn"
    (torn / ".tpusnap").mkdir(parents=True)
    (torn / JOURNAL_FNAME).write_text(
        TakeJournal(take_id="ab" * 16, world_size=1, started_at=0.0).to_json()
    )
    (torn / "blob").write_bytes(b"y" * 100)
    with pytest.raises(RuntimeError, match="TORN|torn"):
        gc_snapshot(str(torn), dry_run=False)
    g = gc_snapshot(str(torn), dry_run=False, reclaim_torn=True)
    assert set(g.reclaimed) == {JOURNAL_FNAME, "blob"}
    assert fsck_snapshot(str(torn)).state == "empty"


def test_gc_torn_keeps_marker_when_deletions_fail(tmp_path):
    """A failed blob deletion must not let gc delete the journal marker:
    the path would become 'foreign' (which gc refuses) instead of
    staying torn and re-runnable."""
    import tpusnap.storage_plugins.fs as fs_mod

    torn = tmp_path / "torn"
    (torn / ".tpusnap").mkdir(parents=True)
    (torn / JOURNAL_FNAME).write_text(
        TakeJournal(take_id="ef" * 16, world_size=1, started_at=0.0).to_json()
    )
    (torn / "blob_a").write_bytes(b"a" * 10)
    (torn / "blob_b").write_bytes(b"b" * 10)

    orig = fs_mod.FSStoragePlugin.delete

    async def flaky_delete(self, p):
        if p == "blob_a":
            raise OSError("injected delete failure")
        await orig(self, p)

    fs_mod.FSStoragePlugin.delete = flaky_delete
    try:
        g = gc_snapshot(str(torn), dry_run=False, reclaim_torn=True)
    finally:
        fs_mod.FSStoragePlugin.delete = orig
    assert g.errors
    assert os.path.exists(torn / JOURNAL_FNAME), "marker must survive"
    assert fsck_snapshot(str(torn)).state == "torn"
    # Re-run finishes the job.
    g = gc_snapshot(str(torn), dry_run=False, reclaim_torn=True)
    assert not g.errors
    assert fsck_snapshot(str(torn)).state == "empty"


def test_gc_dry_run_default_and_orphan_exactness(tmp_path):
    path = tmp_path / "snap"
    state = _state()
    _take(path, state)
    (path / "stray").write_bytes(b"z" * 123)
    g = gc_snapshot(str(path))
    assert g.dry_run and set(g.reclaimed) == {"stray"}
    assert os.path.exists(path / "stray")  # dry-run touched nothing
    g = gc_snapshot(str(path), dry_run=False)
    assert set(g.reclaimed) == {"stray"}
    assert not os.path.exists(path / "stray")
    # Referenced blobs and telemetry sidecars were never candidates.
    assert verify_snapshot(str(path)).clean
    target = {"app": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    Snapshot(str(path)).restore(target)
    for k, v in state.items():
        assert np.array_equal(target["app"][k], v)


def test_fsck_reports_missing_referenced_blob(tmp_path):
    from tpusnap.knobs import override_batching_disabled

    path = tmp_path / "snap"
    with override_batching_disabled(True):
        _take(path, _state())
    report = fsck_snapshot(str(path))
    assert report.state == "committed" and not report.missing_referenced
    # Delete one referenced blob file.
    blob = next(
        os.path.join(dp, f)
        for dp, _, fns in os.walk(path)
        for f in fns
        if not f.startswith(".") and ".tpusnap" not in dp
    )
    os.unlink(blob)
    report = fsck_snapshot(str(path))
    assert report.missing_referenced, report.summary()


# ------------------------------------------------------------- salvage


def test_salvage_records_match_rule(tmp_path):
    """The dual-hash evidence rule: matching (nbytes, CRC32C, XXH64)
    skips the write; any mismatch rewrites."""
    import asyncio

    from tpusnap.io_types import WriteIO
    from tpusnap.lifecycle import (
        JournalingStoragePlugin,
        load_salvage_records,
    )
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    root = tmp_path / "s"
    loop = asyncio.new_event_loop()
    try:
        inner = FSStoragePlugin(str(root))
        plug = JournalingStoragePlugin(inner, rank=0)
        data = b"a" * 4096
        plug.sync_write(WriteIO(path="0/app/w", buf=data), loop)
        records = load_salvage_records(inner, loop, 1)
        assert "0/app/w" in records and records["0/app/w"][0] == 4096

        import tpusnap.telemetry as telemetry

        # Same bytes → salvage skips the write (inner write would
        # overwrite; prove the skip by making inner.write explode).
        plug2 = JournalingStoragePlugin(inner, rank=0, salvage_records=records)
        before = telemetry.counter_value("salvage.blobs_salvaged")

        async def boom(write_io):
            raise AssertionError("matching write must be skipped")

        inner_write = inner.write
        inner.write = boom
        try:
            plug2.sync_write(WriteIO(path="0/app/w", buf=data), loop)
        finally:
            inner.write = inner_write
        assert telemetry.counter_value("salvage.blobs_salvaged") == before + 1

        # Different bytes → rewritten through the inner plugin.
        plug2.sync_write(WriteIO(path="0/app/w", buf=b"b" * 4096), loop)
        assert open(root / "0/app/w", "rb").read() == b"b" * 4096
        plug.sync_close(loop)
        plug2.sync_close(loop)
    finally:
        loop.close()


def test_salvage_records_survive_a_second_crash(tmp_path):
    """A salvage-retake's take-start record write must carry the loaded
    (seeded) records, not an empty map — a second crash early in the
    retake must leave evidence for the third attempt."""
    import asyncio

    from tpusnap.io_types import WriteIO
    from tpusnap.lifecycle import (
        JournalingStoragePlugin,
        load_salvage_records,
    )
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    root = tmp_path / "s"
    loop = asyncio.new_event_loop()
    try:
        inner = FSStoragePlugin(str(root))
        plug = JournalingStoragePlugin(inner, rank=0)
        plug.sync_write(WriteIO(path="0/app/a", buf=b"a" * 256), loop)
        plug.sync_write(WriteIO(path="0/app/b", buf=b"b" * 256), loop)
        records = load_salvage_records(inner, loop, 1)
        assert set(records) == {"0/app/a", "0/app/b"}
        # Retake: seed + eager write (what _take_impl does at start),
        # then "crash" before reprocessing anything.
        plug2 = JournalingStoragePlugin(inner, rank=0, salvage_records=records)
        plug2.sync_seed_record_file(loop)
        # Third attempt still sees both records.
        again = load_salvage_records(inner, loop, 1)
        assert set(again) == {"0/app/a", "0/app/b"}
        plug.sync_close(loop)
        plug2.sync_close(loop)
    finally:
        loop.close()


def test_salvage_record_without_blob_is_dropped(tmp_path):
    """A record whose blob is gone (or resized) must never license a
    write skip — the record-file-outlives-blob-cleanup hazard."""
    import asyncio

    from tpusnap.io_types import WriteIO
    from tpusnap.lifecycle import (
        JournalingStoragePlugin,
        load_salvage_records,
    )
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    root = tmp_path / "s"
    loop = asyncio.new_event_loop()
    try:
        inner = FSStoragePlugin(str(root))
        plug = JournalingStoragePlugin(inner, rank=0)
        plug.sync_write(WriteIO(path="0/app/gone", buf=b"g" * 512), loop)
        plug.sync_write(WriteIO(path="0/app/kept", buf=b"k" * 512), loop)
        os.unlink(root / "0/app/gone")
        records = load_salvage_records(inner, loop, 1)
        assert set(records) == {"0/app/kept"}
        plug.sync_close(loop)
    finally:
        loop.close()


# ------------------------------------------------------------------ CLI


def test_cli_fsck_and_gc(tmp_path, capsys):
    from tpusnap.__main__ import main

    path = tmp_path / "snap"
    _take(path, _state())
    assert main(["fsck", str(path)]) == 0
    assert "committed" in capsys.readouterr().out

    (path / "junk").write_bytes(b"j" * 10)
    assert main(["gc", str(path)]) == 0  # dry-run
    assert os.path.exists(path / "junk")
    assert main(["gc", str(path), "--force"]) == 0
    assert not os.path.exists(path / "junk")

    # torn directory: exit 4 from fsck, gc refuses without --torn
    torn = tmp_path / "torn"
    (torn / ".tpusnap").mkdir(parents=True)
    (torn / JOURNAL_FNAME).write_text(
        TakeJournal(take_id="cd" * 16, world_size=1, started_at=0.0).to_json()
    )
    (torn / "b").write_bytes(b"b")
    assert main(["fsck", str(torn)]) == 4
    assert main(["gc", str(torn), "--force"]) == 1
    assert main(["gc", str(torn), "--force", "--torn"]) == 0
    assert main(["fsck", str(torn)]) == 3  # empty now

    # corrupt metadata: exit 2
    mp = path / ".snapshot_metadata"
    open(mp, "wb").write(open(mp, "rb").read()[:-30])
    assert main(["fsck", str(path)]) == 2
