"""Wedge-proof probe runner (tpusnap/_subproc.py): the hard-timeout
properties that protect the suite and bench from the PJRT tunnel —
returning on time even when a grandchild inherits the output files and
ignores signals, and killing the whole process group."""

import os
import sys
import time

from tpusnap._subproc import run_hard_timeout


def test_success_path_captures_output():
    r = run_hard_timeout(
        [sys.executable, "-c", "import sys; print('out'); sys.stderr.write('err')"],
        timeout_s=30,
    )
    assert not r.timed_out and r.returncode == 0
    assert "out" in r.stdout and "err" in r.stderr


def test_missing_binary_reports_not_raises():
    r = run_hard_timeout(["/nonexistent-binary-xyz"], timeout_s=5)
    assert not r.timed_out and r.returncode == 127


def test_timeout_returns_promptly_despite_pipe_holding_grandchild():
    """The round-4 failure mode: the child spawns a grandchild that
    inherits its stdout and sleeps forever. subprocess.run with
    capture_output would block draining the pipe after the kill; the
    hard-timeout runner must return within bounds, report what the
    child DID print, and take the grandchild down with the group."""
    code = (
        "import os, subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(600)'])\n"
        "print('grandchild', p.pid, flush=True)\n"
        "time.sleep(600)\n"
    )
    t0 = time.monotonic()
    # 5s: enough for the child interpreter to start and print (a 2s
    # window raced cold CPython startup), far below the sleeps.
    r = run_hard_timeout([sys.executable, "-c", code], timeout_s=5)
    elapsed = time.monotonic() - t0
    assert r.timed_out and r.returncode is None
    assert elapsed < 40
    assert "grandchild" in r.stdout  # pre-timeout output preserved
    gpid = int(r.stdout.split()[1])
    # The WHOLE group was SIGKILLed: the grandchild must be gone (it is
    # reparented to init and reaped; allow a moment for that).
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            os.kill(gpid, 0)
        except ProcessLookupError:
            break
        # Still visible: it may be a zombie pending reaping — check.
        try:
            with open(f"/proc/{gpid}/stat") as f:
                if f.read().split(")")[-1].split()[0] == "Z":
                    break
        except OSError:
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"grandchild {gpid} survived the group kill")


def test_bounded_retries_rerun_from_scratch():
    r = run_hard_timeout(
        [sys.executable, "-c", "import time; time.sleep(600)"],
        timeout_s=1,
        retries=2,
    )
    assert r.timed_out and r.attempts == 3
