"""Tests for the flagship transformer + ring attention (models/, ops/).

Runs on the 8-device CPU mesh from conftest.py — the same environment
the driver uses to validate the multi-chip path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpusnap.models import Transformer, TransformerConfig, make_mesh, make_train_step
from tpusnap.models.transformer import init_train_state, train_state_specs
from tpusnap.ops import ring_attention


def _dense_causal_attention(q, k, v):
    d = q.shape[-1]
    s = q.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * d**-0.5
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1), v)


class TestRingAttention:
    def test_single_device_matches_dense(self):
        q, k, v = (
            jax.random.normal(kk, (2, 16, 4, 8), jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(0), 3)
        )
        ref = _dense_causal_attention(q, k, v)
        out = ring_attention(q, k, v, axis_name=None, causal=True)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_ring_matches_dense_on_mesh(self):
        mesh = make_mesh()
        q, k, v = (
            jax.random.normal(kk, (2, 16, 4, 8), jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(1), 3)
        )
        ref = _dense_causal_attention(q, k, v)
        spec = P("data", "fsdp", "tensor", None)
        from tpusnap.models.transformer import _shard_map

        fn = jax.jit(
            _shard_map(
                functools.partial(ring_attention, axis_name="fsdp", causal=True),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
        )
        np.testing.assert_allclose(fn(q, k, v), ref, atol=1e-5)

    def test_non_causal(self):
        q, k, v = (
            jax.random.normal(kk, (1, 8, 2, 4), jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(2), 3)
        )
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * q.shape[-1] ** -0.5
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, -1), v)
        out = ring_attention(q, k, v, axis_name=None, causal=False)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_grads_flow(self):
        q, k, v = (
            jax.random.normal(kk, (1, 8, 2, 4), jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(3), 3)
        )
        g = jax.grad(lambda q: ring_attention(q, k, v).sum())(q)
        assert bool(jnp.all(jnp.isfinite(g)))


_TINY = dict(vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128)


class TestTransformer:
    def test_forward_shapes(self):
        model = Transformer(TransformerConfig(**_TINY))
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = jax.jit(model.apply)(params, tokens)
        assert logits.shape == (2, 16, 128)
        assert logits.dtype == jnp.float32

    @pytest.mark.parametrize("n_experts", [0, 4], ids=["dense", "moe"])
    @pytest.mark.parametrize("ring", [False, True], ids=["noring", "ring"])
    def test_train_step_decreases_loss(self, n_experts, ring):
        mesh = make_mesh()
        cfg = TransformerConfig(
            **_TINY, n_experts=n_experts, use_ring_attention=ring
        )
        model = Transformer(cfg)
        state = init_train_state(model, mesh, jax.random.PRNGKey(0))
        train_step = make_train_step(model, mesh, learning_rate=1e-2)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 128)
        losses = []
        for _ in range(3):
            state, loss = train_step(state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert int(state["opt"]["step"]) == 3

    def test_ring_and_dense_attention_agree(self):
        """The same params produce (numerically) the same loss whether the
        sequence is ring-sharded or not — SP is a pure layout change."""
        mesh = make_mesh()
        base = TransformerConfig(**_TINY)
        model_d = Transformer(base)
        model_r = Transformer(
            TransformerConfig(**_TINY, use_ring_attention=True)
        )
        params = model_d.shard_params(model_d.init(jax.random.PRNGKey(0)), mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
        loss_d = jax.jit(model_d.loss)(params, tokens)
        loss_r = jax.jit(functools.partial(model_r.loss, mesh=mesh))(
            params,
            jax.device_put(tokens, NamedSharding(mesh, P("data", "fsdp"))),
        )
        np.testing.assert_allclose(float(loss_d), float(loss_r), rtol=2e-2)

    def test_param_specs_cover_params(self):
        cfg = TransformerConfig(**_TINY, n_experts=4)
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0))
        specs = model.param_specs()
        assert jax.tree.structure(
            params
        ) == jax.tree.structure(specs, is_leaf=lambda s: isinstance(s, P))

    def test_sharded_train_state_snapshot_roundtrip(self, tmp_path):
        """Checkpoint the fully-sharded train state (fsdp/tp/ep layouts)
        and restore into a zeroed state under the same mesh."""
        from tpusnap import PytreeState, Snapshot
        from tpusnap.test_utils import check_state_dict_eq

        mesh = make_mesh()
        cfg = TransformerConfig(**_TINY, n_experts=4)
        model = Transformer(cfg)
        state = init_train_state(model, mesh, jax.random.PRNGKey(0))
        Snapshot.take(str(tmp_path / "snap"), {"ts": PytreeState(state)})
        target = PytreeState(jax.tree.map(jnp.zeros_like, state))
        Snapshot(str(tmp_path / "snap")).restore({"ts": target})
        assert check_state_dict_eq(state, target.tree)
        for before, after in zip(
            jax.tree.leaves(state), jax.tree.leaves(target.tree)
        ):
            assert after.sharding == before.sharding

    def test_restore_into_different_mesh_shape(self, tmp_path):
        """Elasticity: save under (2,2,2), restore under (1,4,2) — the
        sharded preparer reshards on load."""
        from tpusnap import PytreeState, Snapshot

        cfg = TransformerConfig(**_TINY)
        model = Transformer(cfg)
        mesh_a = make_mesh(mesh_shape=(2, 2, 2))
        state = init_train_state(model, mesh_a, jax.random.PRNGKey(0))
        Snapshot.take(str(tmp_path / "snap"), {"ts": PytreeState(state)})

        mesh_b = make_mesh(mesh_shape=(1, 4, 2))
        state_b = init_train_state(model, mesh_b, jax.random.PRNGKey(7))
        target = PytreeState(state_b)
        Snapshot(str(tmp_path / "snap")).restore({"ts": target})
        for before, after in zip(
            jax.tree.leaves(state), jax.tree.leaves(target.tree)
        ):
            np.testing.assert_array_equal(np.asarray(before), np.asarray(after))


class TestFlashAttention:
    """Pallas flash kernel (ops/flash_attention.py), interpret mode on CPU."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize(
        "shape", [(2, 16, 2, 8), (1, 200, 4, 64)], ids=["tiny", "padded"]
    )
    def test_matches_dense(self, causal, shape):
        from tpusnap.ops import flash_attention
        from tpusnap.ops.flash_attention import _attention_reference

        b, s, h, d = shape
        q, k, v = (
            jax.random.normal(kk, shape, jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(1), 3)
        )
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        ref = _attention_reference(q, k, v, causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_gradients_match_reference(self):
        from tpusnap.ops import flash_attention
        from tpusnap.ops.flash_attention import _attention_reference

        q, k, v = (
            jax.random.normal(kk, (1, 32, 2, 16), jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(2), 3)
        )
        g = jax.grad(lambda *a: flash_attention(*a).sum(), argnums=(0, 1, 2))(
            q, k, v
        )
        gr = jax.grad(
            lambda *a: _attention_reference(*a, True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for got, want in zip(g, gr):
            np.testing.assert_allclose(got, want, atol=2e-5)

    def test_model_forward_flash_vs_reference(self):
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32
        )
        logits = {}
        for impl in ("flash", "reference"):
            cfg = TransformerConfig(
                vocab_size=128,
                d_model=32,
                n_heads=2,
                n_layers=2,
                d_ff=64,
                max_seq_len=16,
                dtype=jnp.float32,
                attention_impl=impl,
            )
            model = Transformer(cfg)
            params = model.init(jax.random.PRNGKey(0))
            logits[impl] = model.apply(params, tokens)
        np.testing.assert_allclose(
            logits["flash"], logits["reference"], atol=1e-4
        )
