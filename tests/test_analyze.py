"""Performance attribution: log2 histograms, critical-path bound
analysis, in-take roofline probes, and the `tpusnap analyze` doctor CLI.

The math tests run on synthetic spans/values with zero sleeps (the
attribution sweep and the histograms are pure functions of recorded
data); the CLI tests drive real takes through `python -m tpusnap
analyze`, including the zero-span/pre-telemetry exit-3 contract that
matches `trace`; the 2-proc test asserts the cross-rank histogram merge
in the metadata rollup.
"""

import glob
import json
import os

import numpy as np
import pytest

from tpusnap import PytreeState, Snapshot, telemetry
from tpusnap.__main__ import main
from tpusnap.analyze import (
    Thresholds,
    analyze,
    attribute_spans,
    classify_span,
    straggler_findings,
    tail_latency_findings,
)
from tpusnap.knobs import (
    override_probe,
    override_telemetry_dir,
    override_telemetry_enabled,
)
from tpusnap.progress import load_restore_traces
from tpusnap.telemetry import IOStats, LogHistogram


def _state(total_bytes=8 << 20, n=4):
    per = total_bytes // n
    return {
        f"w{i}": np.random.default_rng(i).integers(
            0, 255, per, dtype=np.uint8
        )
        for i in range(n)
    }


# ------------------------------------------------------- LogHistogram


def test_log_histogram_bucketing():
    h = LogHistogram()
    for v in (1.0, 1.5, 2.0, 3.99, 4.0, 0.0):
        h.observe(v)
    # [1,2): 1.0, 1.5 -> bucket 0; [2,4): 2.0, 3.99 -> bucket 1;
    # [4,8): 4.0 -> bucket 2; zero -> the zero bucket.
    assert h.buckets[0] == 2
    assert h.buckets[1] == 2
    assert h.buckets[2] == 1
    assert h.count == 6
    assert h.vmax == 4.0
    assert h.vmin == 0.0
    assert abs(h.total - 12.49) < 1e-9


def test_log_histogram_quantiles_exact_at_extremes():
    h = LogHistogram()
    assert h.quantile(0.5) is None  # empty
    h.observe(0.004)
    # Single sample: every quantile is that sample (clamped to max).
    assert h.quantile(0.5) == pytest.approx(0.004)
    assert h.quantile(1.0) == pytest.approx(0.004)
    for _ in range(99):
        h.observe(0.001)
    h.observe(10.0)
    # p50 lives in the 0.001 bucket; max is exact.
    assert h.quantile(0.5) <= 0.002048
    assert h.quantile(1.0) == pytest.approx(10.0)
    # The fat tail is visible: p99 >> p50 once the outlier has weight.
    for _ in range(10):
        h.observe(10.0)
    assert h.quantile(0.99) == pytest.approx(10.0)


def test_log_histogram_merge_preserves_tails():
    a, b = LogHistogram(), LogHistogram()
    for _ in range(100):
        a.observe(0.001)
    b.observe(5.0)  # one rank's outlier
    a.merge(b)
    assert a.count == 101
    assert a.quantile(1.0) == pytest.approx(5.0)
    # Round-trips through the serialized form (the rollup transport).
    c = LogHistogram.from_dict(a.to_dict())
    assert c.count == a.count
    assert c.quantile(1.0) == pytest.approx(5.0)
    assert c.buckets == a.buckets


def test_iostats_quantile_fields_and_merge():
    st = IOStats()
    for _ in range(98):
        st.observe(0.002, 1 << 20)
    st.observe(0.5, 1 << 20)  # tail writes (2% mass so p99 sees them)
    st.observe(0.5, 1 << 20)
    d = st.to_dict()
    assert d["count"] == 100
    assert d["bytes_total"] == 100 << 20
    assert d["p50_s"] <= 0.004096
    assert d["max_s"] == pytest.approx(0.5)
    assert d["p99_s"] >= 0.25  # the tail bucket
    other = IOStats()
    other.merge_dict(d)
    other.merge_dict(d)
    assert other.to_dict()["count"] == 200


def test_merge_io_histograms_across_ranks():
    r0, r1 = IOStats(), IOStats()
    for _ in range(10):
        r0.observe(0.001, 1 << 20)
    r1.observe(2.0, 1 << 20)  # rank 1's straggler write
    merged = telemetry.merge_io_histograms(
        [
            {"write.FSStoragePlugin": r0.to_dict()},
            {"write.FSStoragePlugin": r1.to_dict()},
        ]
    )
    st = merged["write.FSStoragePlugin"]
    assert st["count"] == 11
    assert st["max_s"] == pytest.approx(2.0)


# -------------------------------------------------------- attribution


def test_classify_span_taxonomy():
    assert classify_span("storage_write") == "storage_write"
    assert classify_span("stage_buffer") == "stage"
    assert classify_span("dtoh") == "dtoh"
    assert classify_span("checksum_late") == "checksum"
    assert classify_span("cow_verify") == "checksum"
    assert classify_span("comm.barrier") == "barrier"
    assert classify_span("kv.barrier_arrive") == "barrier"
    assert classify_span("budget_wait") == "budget_wait"
    # Containers and unknown names never attribute.
    assert classify_span("stage_window") is None
    assert classify_span("probe_roofline") is None
    assert classify_span("some_future_span") is None


def test_attribution_single_category_full_coverage():
    att = attribute_spans([("storage_write", 0.0, 10.0)], wall_s=10.0)
    assert att.attributed == {"storage_write": pytest.approx(10.0)}
    assert att.unattributed_s == pytest.approx(0.0)
    assert att.verdict() == ("storage_write", pytest.approx(1.0))


def test_attribution_io_wins_overlap_and_glue_is_unattributed():
    # stage [0,4], write [2,8], wall 10: write owns [2,8] (I/O-first
    # tiebreak), stage only its solo [0,2], [8,10] is glue.
    att = attribute_spans(
        [("stage_buffer", 0.0, 4.0), ("storage_write", 2.0, 6.0)],
        wall_s=10.0,
    )
    assert att.attributed["storage_write"] == pytest.approx(6.0)
    assert att.attributed["stage"] == pytest.approx(2.0)
    assert att.unattributed_s == pytest.approx(2.0)
    # Raw busy time ignores the overlap exclusivity.
    assert att.busy["stage"] == pytest.approx(4.0)
    assert att.coverage == pytest.approx(0.8)


def test_attribution_waits_only_when_idle():
    # budget_wait under in-flight I/O is storage-bound (writes are the
    # only budget source); a bare budget_wait is budget-bound.
    att = attribute_spans(
        [
            ("budget_wait", 0.0, 5.0),
            ("storage_write", 0.0, 5.0),
            ("budget_wait", 5.0, 3.0),
        ],
        wall_s=8.0,
    )
    assert att.attributed["storage_write"] == pytest.approx(5.0)
    assert att.attributed["budget_wait"] == pytest.approx(3.0)
    assert att.unattributed_s == pytest.approx(0.0)


def test_attribution_barrier_lowest_priority_and_clipping():
    att = attribute_spans(
        [
            ("comm.barrier", 0.0, 4.0),
            ("checksum", 1.0, 2.0),
            ("storage_read", 6.0, 100.0),  # clipped to wall
            ("stage_window", 0.0, 10.0),  # container: ignored
        ],
        wall_s=10.0,
    )
    assert att.attributed["checksum"] == pytest.approx(2.0)
    assert att.attributed["barrier"] == pytest.approx(2.0)  # [0,1]+[3,4]
    assert att.attributed["storage_read"] == pytest.approx(4.0)
    assert att.unattributed_s == pytest.approx(2.0)  # [4,6]
    total = sum(att.attributed.values()) + att.unattributed_s
    assert total == pytest.approx(10.0)


def test_attribution_overlapping_same_category_not_double_counted():
    # 16 concurrent writes over the same 5 s attribute 5 s, not 80.
    spans = [("storage_write", 0.0, 5.0) for _ in range(16)]
    att = attribute_spans(spans, wall_s=5.0)
    assert att.attributed["storage_write"] == pytest.approx(5.0)
    assert att.busy["storage_write"] == pytest.approx(5.0)


def test_attribution_empty_spans():
    att = attribute_spans([], wall_s=3.0)
    assert att.attributed == {}
    assert att.unattributed_s == pytest.approx(3.0)
    assert att.verdict() is None


# ----------------------------------------------------------- findings


def test_tail_latency_finding_fires_on_fat_tail():
    st = IOStats()
    for _ in range(98):
        st.observe(0.002, 1 << 20)
    st.observe(0.9, 1 << 20)
    st.observe(0.9, 1 << 20)
    hist = {"write.FSStoragePlugin": st.to_dict()}
    out = tail_latency_findings(hist, Thresholds(p99_ratio=20.0))
    assert len(out) == 1
    assert out[0].severity == "warn"
    assert "write.FSStoragePlugin" in out[0].message
    # Below the ratio threshold: quiet.
    assert not tail_latency_findings(hist, Thresholds(p99_ratio=10_000.0))
    # Too few samples to call a tail: quiet.
    tiny = IOStats()
    tiny.observe(0.001, 1)
    tiny.observe(1.0, 1)
    assert not tail_latency_findings(
        {"write.X": tiny.to_dict()}, Thresholds(p99_ratio=2.0)
    )


def test_straggler_finding_from_rollup_skew():
    rollup = {
        "ranks": 4,
        "phase_skew": {
            "stage": {"p50_s": 1.0, "max_s": 3.5, "max_rank": 2, "skew": 3.5}
        },
    }
    out = straggler_findings(rollup, Thresholds(max_skew=2.0))
    assert len(out) == 1 and "rank 2" in out[0].message
    # Single-rank rollups have no stragglers by construction.
    assert not straggler_findings({**rollup, "ranks": 1}, Thresholds())


def test_analyze_report_shape_on_synthetic_docs():
    doc = {
        "summary": {
            "rank": 0,
            "take_wall_s": 10.0,
            "stages": {"storage_write": {"count": 1}},
        },
        "traceEvents": [
            {
                "name": "storage_write",
                "ph": "X",
                "cat": "op",
                "ts": 0.0,
                "dur": 9e6,
            },
            {"name": "stage", "ph": "X", "cat": "phase", "ts": 0, "dur": 1e7},
        ],
    }
    report = analyze({}, {0: doc}, kind="take")
    assert report["bound_by"] == "storage_write"
    assert report["bound_pct"] == pytest.approx(90.0)
    assert "TPUSNAP" in report["advice"]
    assert report["attribution"]["coverage"] == pytest.approx(0.9)
    assert report["check_failed"] is False


# ----------------------------------------------------- probe runner


def test_probe_records_samples_and_cleans_up(tmp_path):
    snap = str(tmp_path / "snap")
    with override_probe(True, interval_bytes=1 << 20, probe_bytes=1 << 20):
        Snapshot.take(snap, {"m": PytreeState(_state())})
    s = telemetry.LAST_TAKE_SUMMARY
    assert s["probe"]["probes"] >= 1
    assert s["probe"]["write_gbps_p50"] > 0
    assert s["probe"]["read_gbps_p50"] > 0
    assert 0 < s["roofline_fraction"]
    assert s["counters"]["probe.probes"] >= 1
    # Probe files are transient: none survive the take.
    assert not glob.glob(os.path.join(snap, ".tpusnap", "probe", "*"))
    # The probe rides the rollup too (single-rank fold).
    md = json.load(open(os.path.join(snap, ".snapshot_metadata")))
    rollup = md["extras"]["telemetry"]
    assert rollup["roofline_fraction"] == s["roofline_fraction"]
    assert rollup["probe"]["probes"] == s["probe"]["probes"]
    # And the history event carries the drift-immune fraction.
    from tpusnap.history import event_from_summary

    ev = event_from_summary("take", s)
    assert ev["roofline_fraction"] == s["roofline_fraction"]
    assert ev["probe_write_gbps"] == s["probe"]["write_gbps_p50"]


def test_probe_off_by_default(tmp_path):
    Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    s = telemetry.LAST_TAKE_SUMMARY
    assert "probe" not in s
    assert "roofline_fraction" not in s


def test_small_take_still_gets_one_probe(tmp_path):
    # Interval far above the take's bytes: the end-of-drain fallback
    # still measures once, so no probe-enabled take is fraction-less.
    with override_probe(True, interval_bytes=1 << 40, probe_bytes=1 << 20):
        Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    assert telemetry.LAST_TAKE_SUMMARY["probe"]["probes"] == 1


def test_probe_runner_stands_down_after_failure():
    """One failed probe disables probing for the take (one WARNING, no
    retry storm) — and the drain-end fallback respects the stand-down."""
    import asyncio

    from tpusnap.io_types import StoragePlugin
    from tpusnap.scheduler import _ProbeRunner

    class BoomPlugin(StoragePlugin):
        async def write(self, write_io):
            raise OSError("probe traffic rejected")

        async def read(self, read_io):
            raise OSError("nope")

        async def delete(self, path):
            pass

    with override_probe(True, interval_bytes=1 << 20, probe_bytes=1 << 20):
        tele = telemetry.TakeTelemetry(rank=0, enabled=True)
        try:
            runner = _ProbeRunner(BoomPlugin(), rank=0, tele=tele)
            runner.note_written(1 << 30)
            assert runner.due
            asyncio.run(runner.run())
        finally:
            # A bare TakeTelemetry (no end_take) starts an RSS sampler
            # thread; stop it or it outlives the test forever.
            tele.finalize()
    assert runner.ran == 0
    assert runner._failed
    runner.note_written(1 << 30)
    assert not runner.due  # stood down: never due again this take
    assert "probe" not in tele.summary()


def test_probe_excluded_from_async_blocked_window(tmp_path):
    """Probes never run inside a pipelined async take's blocked window
    — they would bill their I/O to async_blocked_s, the metric
    async_take exists to minimize. Every probe span starts after the
    blocked window closed."""
    snap = str(tmp_path / "snap")
    with override_probe(True, interval_bytes=1 << 20, probe_bytes=1 << 20):
        pending = Snapshot.async_take(
            snap, {"m": PytreeState(_state(total_bytes=16 << 20))}
        )
        pending.wait()
    s = telemetry.LAST_TAKE_SUMMARY
    assert s["probe"]["probes"] >= 1
    blocked_s = s["async_blocked_s"]
    doc = json.load(
        open(os.path.join(snap, ".tpusnap", "telemetry", "rank_0.json"))
    )
    probe_starts = [
        ev["ts"] / 1e6
        for ev in doc["traceEvents"]
        if ev.get("name") == "probe_roofline" and ev.get("ph") == "X"
    ]
    assert probe_starts, "no probe spans recorded"
    assert all(ts >= blocked_s for ts in probe_starts), (
        probe_starts,
        blocked_s,
    )


def test_stranded_probe_file_does_not_make_aborted_dir_foreign(tmp_path):
    """A probe stream a flaky backend's failed cleanup strands in an
    otherwise-cleaned (aborted) dir must not classify the path as
    'foreign' — gc refuses foreign, which would lock the checkpoint
    path against reuse. It reads as empty/reusable, like a leftover
    heartbeat record."""
    from tpusnap.lifecycle import fsck_snapshot

    d = tmp_path / "snap" / ".tpusnap" / "probe"
    d.mkdir(parents=True)
    (d / "rank_0_0.bin").write_bytes(b"x" * 1024)
    report = fsck_snapshot(str(tmp_path / "snap"))
    assert report.state == "empty", (report.state, report.detail)


# ------------------------------------------- probe runner: read path


def _probe_restore(tmp_path, total_bytes=64 << 20, n=8):
    """Take, then restore with in-restore probes on. Returns the
    restore summary and rank 0's persisted restore trace doc."""
    from tpusnap import compress

    snap = str(tmp_path / "snap")
    state = _state(total_bytes=total_bytes, n=n)
    Snapshot.take(snap, {"m": PytreeState(state)})
    compress._reset_ceilings()
    with override_telemetry_dir(str(tmp_path / "teledir")):
        with override_probe(
            True, interval_bytes=16 << 20, probe_bytes=1 << 20
        ):
            Snapshot(snap).restore(
                {
                    "m": PytreeState(
                        {k: np.zeros_like(v) for k, v in state.items()}
                    )
                }
            )
        docs = load_restore_traces(snap)
    return snap, telemetry.LAST_RESTORE_SUMMARY, docs[0]


def test_restore_probe_feeds_read_lane_and_history(tmp_path):
    """In-restore probes (TPUSNAP_PROBE=1): the restore summary gets
    the read-lane fraction, the ceiling registry gets a read-lane
    entry, no probe scratch survives, and the history event carries
    the drift-immune read fields."""
    from tpusnap import compress
    from tpusnap.history import event_from_summary

    snap, s, _doc = _probe_restore(tmp_path)
    assert s["probe"]["probes"] >= 1
    assert s["probe"]["read_gbps_p50"] > 0
    assert 0 < s["restore_roofline_fraction"]
    # The write-lane fraction belongs to takes — a restore summary
    # must not grow one.
    assert "roofline_fraction" not in s
    lanes = {lane for (_label, lane) in compress.pipe_ceilings_snapshot()}
    assert "read" in lanes
    assert not glob.glob(os.path.join(snap, ".tpusnap", "probe", "*"))
    ev = event_from_summary("restore", s)
    assert ev["restore_roofline_fraction"] == s["restore_roofline_fraction"]
    assert ev["probe_read_gbps"] == s["probe"]["read_gbps_p50"]
    assert "roofline_fraction" not in ev


def test_restore_probe_spans_outside_read_windows(tmp_path):
    """Probes only run while no blob read is in flight — a probe
    interleaved with reads would bill its own I/O to the storage_read
    window it exists to price. No probe span may overlap any
    storage_read span in the restore trace."""
    _snap, s, doc = _probe_restore(tmp_path)
    assert s["probe"]["probes"] >= 1
    spans = {"probe_roofline": [], "storage_read": []}
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "X" and ev.get("name") in spans:
            spans[ev["name"]].append((ev["ts"], ev["ts"] + ev["dur"]))
    assert spans["probe_roofline"] and spans["storage_read"]
    for p0, p1 in spans["probe_roofline"]:
        for r0, r1 in spans["storage_read"]:
            assert p1 <= r0 or r1 <= p0, (
                "probe span overlaps a read window",
                (p0, p1),
                (r0, r1),
            )


def test_restore_probe_stands_down_on_read_lane():
    """The stand-down contract holds on the restore side too: one
    failed probe disables probing for the restore, and the summary
    grows neither a probe block nor restore_roofline_fraction."""
    import asyncio

    from tpusnap.io_types import StoragePlugin
    from tpusnap.scheduler import _ProbeRunner

    class BoomPlugin(StoragePlugin):
        async def write(self, write_io):
            raise OSError("probe scratch rejected")

        async def read(self, read_io):
            raise OSError("nope")

        async def delete(self, path):
            pass

    with override_probe(True, interval_bytes=1 << 20, probe_bytes=1 << 20):
        tele = telemetry.TakeTelemetry(rank=0, enabled=True)
        tele.meta["kind"] = "restore"
        try:
            runner = _ProbeRunner(BoomPlugin(), rank=0, tele=tele)
            runner.note_written(1 << 30)
            assert runner.due
            asyncio.run(runner.run())
        finally:
            tele.finalize()
    assert runner.ran == 0
    assert runner._failed
    runner.note_written(1 << 30)
    assert not runner.due  # stood down for the rest of this restore
    s = tele.summary()
    assert "probe" not in s
    assert "restore_roofline_fraction" not in s


def test_quantile_geometric_interpolation_stays_in_bucket():
    # The interpolated estimate never leaves the bucket that holds the
    # target rank, and clamps to the exact observed extremes.
    h = LogHistogram()
    for _ in range(50):
        h.observe(0.001)
    for _ in range(50):
        h.observe(0.003)
    p25, p75 = h.quantile(0.25), h.quantile(0.75)
    assert 0.0009765625 <= p25 <= 0.001953125  # 0.001's bucket
    assert 0.001953125 <= p75 <= 0.00390625  # 0.003's bucket
    assert p25 >= h.vmin and p75 <= h.vmax


# -------------------------------------------------- take histograms


def test_take_summary_records_io_histograms(tmp_path):
    Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    s = telemetry.LAST_TAKE_SUMMARY
    hist = s["io_histograms"]
    write = hist["write.FSStoragePlugin"]
    assert write["count"] > 0
    assert write["bytes_total"] >= 8 << 20
    assert write["p50_s"] is not None and write["p99_s"] >= write["p50_s"]
    # The rollup in metadata carries the merged copy. It is snapshotted
    # BEFORE the commit barrier, so the trace-sidecar and metadata
    # writes that follow are in the final summary but not in it.
    md = json.load(
        open(os.path.join(tmp_path, "snap", ".snapshot_metadata"))
    )
    merged = md["extras"]["telemetry"]["io_histograms"][
        "write.FSStoragePlugin"
    ]
    assert 0 < merged["count"] <= write["count"]
    assert merged["p99_s"] is not None


def test_histograms_recorded_even_with_telemetry_off(tmp_path):
    # Histograms are always-on like the counters (the knob gates spans).
    telemetry.reset_global_io_histograms()
    with override_telemetry_enabled(False):
        Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    g = telemetry.global_io_histograms_snapshot()
    assert g["write.FSStoragePlugin"]["count"] > 0


# ------------------------------------------------------ analyze CLI


def _probe_take(tmp_path):
    snap = str(tmp_path / "snap")
    with override_probe(True, interval_bytes=4 << 20, probe_bytes=1 << 20):
        Snapshot.take(snap, {"m": PytreeState(_state(total_bytes=16 << 20))})
    return snap


def test_analyze_cli_prints_verdict(tmp_path, capsys):
    snap = _probe_take(tmp_path)
    rc = main(["analyze", snap])
    out = capsys.readouterr().out
    assert rc == 0
    assert "BOUND BY:" in out
    assert "attribution" in out
    assert "storage-boundary latency" in out
    assert "roofline:" in out


def test_analyze_cli_json_shape(tmp_path, capsys):
    snap = _probe_take(tmp_path)
    rc = main(["analyze", snap, "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["kind"] == "take"
    assert doc["bound_by"] in (
        "storage_write",
        "stage",
        "checksum",
        "dtoh",
    )
    assert 0 < doc["attribution"]["coverage"] <= 1
    assert "write.FSStoragePlugin" in doc["io_histograms"]
    assert isinstance(doc["findings"], list)
    assert "roofline_fraction" in doc


def test_analyze_cli_check_exit_codes(tmp_path, capsys):
    snap = _probe_take(tmp_path)
    # Impossible roofline bar -> the warn finding fires -> exit 2.
    rc = main(["analyze", snap, "--check", "--min-roofline", "1.1"])
    assert rc == 2
    capsys.readouterr()
    # Thresholds that cannot fire -> healthy -> exit 0.
    rc = main(
        [
            "analyze",
            snap,
            "--check",
            "--min-roofline",
            "0",
            "--p99-ratio",
            "1e9",
            "--max-skew",
            "1e9",
        ]
    )
    assert rc == 0


def test_analyze_cli_zero_spans_exits_3(tmp_path, capsys):
    # Knob-off take: counters roll up but zero spans anywhere — the
    # doctor has nothing to attribute; one-liner + exit 3 like `trace`.
    with override_telemetry_enabled(False):
        Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    rc = main(["analyze", str(tmp_path / "snap")])
    captured = capsys.readouterr()
    assert rc == 3
    assert "no telemetry recorded" in captured.err


def test_analyze_cli_pre_telemetry_snapshot_exits_3(tmp_path, capsys):
    # Simulate a pre-telemetry snapshot: strip the trace sidecar and
    # the rollup extras from a committed snapshot.
    import shutil

    snap = str(tmp_path / "snap")
    Snapshot.take(snap, {"m": PytreeState(_state())})
    shutil.rmtree(os.path.join(snap, ".tpusnap", "telemetry"))
    md_path = os.path.join(snap, ".snapshot_metadata")
    from tpusnap.manifest import decode_metadata, encode_metadata

    md = decode_metadata(open(md_path, "rb").read())
    md.extras = {}
    with open(md_path, "wb") as f:
        f.write(encode_metadata(md))
    rc = main(["analyze", snap])
    captured = capsys.readouterr()
    assert rc == 3
    assert "no telemetry recorded" in captured.err


def test_analyze_cli_restore(tmp_path, capsys):
    from tpusnap.knobs import override_telemetry_dir

    snap = str(tmp_path / "snap")
    state = _state()
    Snapshot.take(snap, {"m": PytreeState(state)})
    with override_telemetry_dir(str(tmp_path / "tele")):
        target = {k: np.zeros_like(v) for k, v in state.items()}
        Snapshot(snap).restore({"m": PytreeState(target)})
        rc = main(["analyze", snap, "--restore", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["kind"] == "restore"
    assert doc["bound_by"] in ("storage_read", "consume")


def test_analyze_cli_history_context(tmp_path, capsys):
    from tpusnap.knobs import override_telemetry_dir

    with override_telemetry_dir(str(tmp_path / "tele")):
        snap = _probe_take(tmp_path)
        rc = main(["analyze", snap, "--history", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["history"]["events"] >= 1
    assert "throughput_gbps" in doc["history"]


def test_cli_help_lists_analyze(capsys):
    rc = main(["--help"])
    assert rc == 0
    assert "analyze" in capsys.readouterr().out


# ------------------------------------------------------- distributed


def _world_histogram_take(snap_dir):
    import jax.numpy as jnp

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    state = StateDict(
        w=jnp.arange(8192, dtype=jnp.float32) * (comm.rank + 1),
        b=jnp.ones(64, jnp.float32),
    )
    Snapshot.take(snap_dir, {"model": state})
    comm.barrier()
    if comm.rank == 0:
        per_rank_counts = []
        for r in range(comm.world_size):
            p = os.path.join(
                snap_dir, ".tpusnap", "telemetry", f"rank_{r}.json"
            )
            doc = json.load(open(p))
            hist = doc["summary"]["io_histograms"]
            per_rank_counts.append(hist["write.FSStoragePlugin"]["count"])
            assert per_rank_counts[-1] > 0, f"rank {r} recorded no writes"
        md = json.load(open(os.path.join(snap_dir, ".snapshot_metadata")))
        merged = md["extras"]["telemetry"]["io_histograms"][
            "write.FSStoragePlugin"
        ]
        # The rollup merge is the SUM of the per-rank histograms —
        # bucket counts included, so one rank's tail survives the fold.
        assert merged["count"] == sum(per_rank_counts), (
            merged,
            per_rank_counts,
        )
        assert merged["p99_s"] is not None


@pytest.mark.distributed
def test_distributed_histogram_merge_in_rollup(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    run_subprocess_world(
        _world_histogram_take, world_size=2, args=[str(tmp_path / "snap")]
    )


def _world_probe_restore(snap_dir):
    import numpy as np

    from tpusnap import PytreeState, Snapshot, telemetry
    from tpusnap.comm import get_communicator
    from tpusnap.knobs import override_probe
    from tpusnap.progress import load_restore_traces
    from tpusnap.telemetry import rollup_summaries

    comm = get_communicator()
    state = {"w": np.arange(1 << 21, dtype=np.uint8) + comm.rank}
    Snapshot.take(snap_dir, {"m": PytreeState(state)})
    comm.barrier()
    with override_probe(True, interval_bytes=1 << 20, probe_bytes=1 << 20):
        Snapshot(snap_dir).restore(
            {"m": PytreeState({"w": np.zeros(1 << 21, np.uint8)})}
        )
    s = telemetry.LAST_RESTORE_SUMMARY
    assert s.get("restore_roofline_fraction"), sorted(s)
    comm.barrier()
    if comm.rank == 0:
        # Every rank persisted a restore trace; the cross-rank fold
        # carries the read-lane fraction (fleet p50) and the probe
        # aggregate — what `analyze --restore` and the Prometheus
        # gauge read.
        docs = load_restore_traces(snap_dir)
        assert sorted(docs) == [0, 1], sorted(docs)
        roll = rollup_summaries([d["summary"] for d in docs.values()])
        assert roll["restore_roofline_fraction"] > 0
        assert roll["probe"]["read_gbps_p50"] > 0


@pytest.mark.distributed
def test_distributed_restore_rollup_carries_read_fraction(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    run_subprocess_world(
        _world_probe_restore,
        world_size=2,
        args=[str(tmp_path / "snap")],
        extra_env={"TPUSNAP_TELEMETRY_DIR": str(tmp_path / "teledir")},
    )
