"""Fleet metrics export tests: Prometheus textfile format self-check
(parseable, # HELP/# TYPE, monotonic counters across takes), JSONL event
sink lines + rotation, env-driven sink installation/reconfiguration, the
restore-summary export path, and the take-overhead guard with both
export sinks enabled (acceptance criteria of the fleet observability
PR).
"""

import json
import logging
import os
import time

import numpy as np
import pytest

from tpusnap import (
    FaultPlan,
    JsonlEventSink,
    PrometheusTextfileSink,
    PytreeState,
    Snapshot,
)
from tpusnap import metrics_export
from tpusnap.knobs import (
    override_history_enabled,
    override_metrics_dir,
    override_metrics_export,
    override_telemetry_enabled,
    override_telemetry_dir,
)
from tpusnap.metrics_export import install_env_sinks, parse_prometheus_textfile


def _state(total_bytes=1 << 20, n=2):
    per = max(total_bytes // n // 4, 16)
    return {f"w{i}": np.arange(per, dtype=np.float32) + i for i in range(n)}


@pytest.fixture
def metrics_env(tmp_path):
    """Isolated metrics + telemetry dirs, history off (these tests are
    about the export sinks), env sinks reconciled on entry and exit so
    no sink leaks into other tests."""
    mdir = str(tmp_path / "metrics")
    with override_telemetry_dir(str(tmp_path / "tele")), override_metrics_dir(
        mdir
    ), override_history_enabled(False):
        yield mdir
    install_env_sinks()  # spec reverted with the env: unregisters


def _prom_path(mdir, rank=0):
    # The default filename carries the job id (collision fix for two
    # jobs sharing one textfile dir) — host-pid derived unless
    # TPUSNAP_JOB_ID is set.
    from tpusnap.knobs import get_job_id

    return os.path.join(mdir, f"tpusnap_{get_job_id()}_rank{rank}.prom")


def _jsonl_events(mdir):
    p = os.path.join(mdir, "events.jsonl")
    if not os.path.exists(p):
        return []
    return [json.loads(ln) for ln in open(p) if ln.strip()]


# ------------------------------------------------- prometheus textfile


def test_prom_textfile_format_and_monotonic_counters(tmp_path, metrics_env):
    with override_metrics_export("prom"):
        Snapshot.take(str(tmp_path / "s1"), {"m": PytreeState(_state())})
        first = parse_prometheus_textfile(open(_prom_path(metrics_env)).read())
        Snapshot.take(str(tmp_path / "s2"), {"m": PytreeState(_state())})
        text = open(_prom_path(metrics_env)).read()
    # Format self-check: strict parse enforces that every sampled metric
    # carries its # HELP and # TYPE lines and every sample is numeric.
    second = parse_prometheus_textfile(text)
    for name in (
        "tpusnap_take_seconds",
        "tpusnap_takes_total",
        "tpusnap_bytes_written_total",
        "tpusnap_retry_total",
        "tpusnap_retry_attempts_total",
        "tpusnap_stall_episodes_total",
        "tpusnap_budget_high_water_bytes",
        "tpusnap_peak_rss_delta_bytes",
    ):
        assert name in second, f"missing metric {name}"
        assert second[name].get("help") and second[name].get("type")
    assert second["tpusnap_take_seconds"]["type"] == "gauge"
    assert second["tpusnap_bytes_written_total"]["type"] == "counter"

    def only(metrics, name):
        return next(iter(metrics[name]["samples"].values()))

    # Monotonic counters across two consecutive takes (the exported
    # domain is process-global, so rate() works).
    assert only(second, "tpusnap_takes_total") == only(
        first, "tpusnap_takes_total"
    ) + 1
    assert only(second, "tpusnap_bytes_written_total") > only(
        first, "tpusnap_bytes_written_total"
    )
    assert only(second, "tpusnap_take_seconds") > 0
    # rank label present on every sample.
    for meta in second.values():
        for labels in meta["samples"]:
            assert 'rank="0"' in labels


def test_prom_atomic_rewrite_no_temp_debris(tmp_path, metrics_env):
    with override_metrics_export("prom"):
        Snapshot.take(str(tmp_path / "s"), {"m": PytreeState(_state())})
    assert not [f for f in os.listdir(metrics_env) if ".tmp." in f]


def test_prom_exports_storage_latency_quantiles(tmp_path, metrics_env):
    """Histogram quantiles from the process-global I/O histograms:
    summary-typed ``tpusnap_storage_write_seconds{quantile=...,plugin=
    ...}`` series, surviving the strict format self-check, quantiles
    ordered, and the monotonic-domain rule untouched (quantiles are
    point-in-time; only *_total families are counters)."""
    # The exported domain is process-global: earlier tests' backends
    # (fsspec doubles, chaos runs) would otherwise share the family.
    from tpusnap import telemetry

    telemetry.reset_global_io_histograms()
    with override_metrics_export("prom"):
        Snapshot.take(str(tmp_path / "s"), {"m": PytreeState(_state())})
        text = open(_prom_path(metrics_env)).read()
    metrics = parse_prometheus_textfile(text)
    fam = metrics["tpusnap_storage_write_seconds"]
    assert fam["type"] == "summary"
    by_q = {}
    for labels, value in fam["samples"].items():
        assert 'plugin="FSStoragePlugin"' in labels
        assert 'rank="0"' in labels
        for q in ("0.5", "0.95", "0.99"):
            if f'quantile="{q}"' in labels:
                by_q[q] = value
    assert set(by_q) == {"0.5", "0.95", "0.99"}
    assert 0 < by_q["0.5"] <= by_q["0.95"] <= by_q["0.99"]
    # The read family appears once reads happen (a restore).
    state = _state()
    with override_metrics_export("prom"):
        Snapshot(str(tmp_path / "s")).restore(
            {"m": PytreeState({k: np.zeros_like(v) for k, v in state.items()})}
        )
        text = open(_prom_path(metrics_env)).read()
    assert (
        parse_prometheus_textfile(text)["tpusnap_storage_read_seconds"][
            "type"
        ]
        == "summary"
    )


@pytest.mark.chaos
def test_prom_retry_classification_labels(tmp_path, metrics_env):
    with override_metrics_export("prom"):
        Snapshot.take(
            "chaos+fs://" + str(tmp_path / "chaos_snap"),
            {"m": PytreeState(_state())},
            storage_options={
                "fault_plan": FaultPlan(seed=3, transient_per_op=1)
            },
        )
        text = open(_prom_path(metrics_env)).read()
    parsed = parse_prometheus_textfile(text)
    labels = list(parsed["tpusnap_retry_total"]["samples"])
    assert any(
        'classification="transient.write.InjectedFaultError"' in s
        for s in labels
    ), labels


def test_prom_sink_direct_use(tmp_path):
    """The sink is a plain MetricsSink usable without the env knobs."""
    sink = PrometheusTextfileSink(str(tmp_path))
    sink.on_take_summary(
        {
            "rank": 3,
            "completed": True,
            "take_wall_s": 1.5,
            "counters": {},
            "gauges": {"scheduler.budget_used_bytes": 1024.0},
        }
    )
    text = open(_prom_path(tmp_path, rank=3)).read()
    parsed = parse_prometheus_textfile(text)
    samples = parsed["tpusnap_take_seconds"]["samples"]
    assert list(samples.values()) == [1.5]
    assert 'rank="3"' in next(iter(samples))
    budget = parsed["tpusnap_budget_high_water_bytes"]["samples"]
    assert list(budget.values()) == [1024.0]


def test_prom_sink_ignores_aborted_summaries(tmp_path):
    """end_take publishes aborted takes' summaries too; the 'last
    completed take' gauge and 'completed takes' counter must not
    absorb them."""
    sink = PrometheusTextfileSink(str(tmp_path))
    sink.on_take_summary(
        {"rank": 0, "completed": True, "take_wall_s": 1.5, "counters": {}}
    )
    sink.on_take_summary(
        {"rank": 0, "take_wall_s": 0.2, "counters": {}}  # aborted
    )
    parsed = parse_prometheus_textfile(open(_prom_path(tmp_path)).read())
    assert list(parsed["tpusnap_take_seconds"]["samples"].values()) == [1.5]
    assert list(parsed["tpusnap_takes_total"]["samples"].values()) == [1]


def test_parse_prometheus_textfile_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus_textfile("tpusnap_x 1\n")  # sample without TYPE
    with pytest.raises(ValueError):
        parse_prometheus_textfile(
            "# HELP tpusnap_x h\n# TYPE tpusnap_x counter\ntpusnap_x notanum\n"
        )
    with pytest.raises(ValueError):
        parse_prometheus_textfile(
            "# TYPE tpusnap_x bogus_type\ntpusnap_x 1\n"
        )


# ------------------------------------------------------ jsonl event sink


def test_jsonl_sink_take_and_restore_lines(tmp_path, metrics_env):
    with override_metrics_export("jsonl"):
        path = str(tmp_path / "snap")
        Snapshot.take(path, {"m": PytreeState(_state())})
        target = {k: np.zeros_like(v) for k, v in _state().items()}
        Snapshot(path).restore({"m": PytreeState(target)})
    events = _jsonl_events(metrics_env)
    kinds = [e["kind"] for e in events]
    assert kinds == ["take", "restore"]
    take, restore = events
    assert take["rank"] == 0 and take["completed"] is True
    assert take["bytes"] > 0 and take["throughput_gbps"] > 0
    assert restore["bytes"] > 0
    assert take["take_id"]


def test_jsonl_rotation_bound(tmp_path):
    summaries = {
        "rank": 0,
        "completed": True,
        "take_wall_s": 1.0,
        "counters": {"storage.bytes_written": 123456},
    }
    sink = JsonlEventSink(str(tmp_path), max_bytes=4096)  # floor of the bound
    for _ in range(64):
        sink.on_take_summary(dict(summaries))
    main, rotated = sink.path(), sink.path() + ".1"
    assert os.path.exists(rotated)
    assert os.path.getsize(main) <= 4096
    # Every surviving line parses.
    for p in (main, rotated):
        for ln in open(p):
            assert json.loads(ln)["kind"] == "take"


# -------------------------------------------------- env-driven installing


def test_env_install_idempotent_and_reconfigurable(tmp_path, metrics_env):
    with override_metrics_export("prom,jsonl"):
        Snapshot.take(str(tmp_path / "a"), {"m": PytreeState(_state())})
        Snapshot.take(str(tmp_path / "b"), {"m": PytreeState(_state())})
        # One sink per format despite two installs: 2 takes -> 2 lines.
        assert len(_jsonl_events(metrics_env)) == 2
        assert os.path.exists(_prom_path(metrics_env))
    # Spec reverted: the next take must not export.
    n = len(_jsonl_events(metrics_env))
    Snapshot.take(str(tmp_path / "c"), {"m": PytreeState(_state())})
    assert len(_jsonl_events(metrics_env)) == n


def test_unknown_export_format_skipped_with_warning(caplog, metrics_env):
    with override_metrics_export("bogus,jsonl"):
        with caplog.at_level(logging.WARNING, logger="tpusnap.knobs"):
            install_env_sinks()
        assert any("bogus" in r.message for r in caplog.records)
        with metrics_export._env_lock:
            kinds = [type(s).__name__ for s in metrics_export._env_sinks]
        assert kinds == ["JsonlEventSink"]
        # Warn-once per process: a typo'd knob in a job checkpointing
        # every few minutes must not spam one WARNING per take.
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="tpusnap.knobs"):
            install_env_sinks()
        assert not any("bogus" in r.message for r in caplog.records)
    install_env_sinks()


def test_export_disabled_takes_write_nothing(tmp_path, metrics_env):
    Snapshot.take(str(tmp_path / "s"), {"m": PytreeState(_state())})
    assert not os.path.exists(_prom_path(metrics_env))
    assert not _jsonl_events(metrics_env)


def test_telemetry_off_still_exports_summaries(tmp_path, metrics_env):
    """Counters are always-on and the summary still publishes with
    TPUSNAP_TELEMETRY=0 — fleet export must not go dark just because
    span capture is off."""
    with override_metrics_export("prom,jsonl"), override_telemetry_enabled(
        False
    ):
        Snapshot.take(str(tmp_path / "s"), {"m": PytreeState(_state())})
    events = _jsonl_events(metrics_env)
    assert len(events) == 1 and events[0]["bytes"] > 0
    parsed = parse_prometheus_textfile(open(_prom_path(metrics_env)).read())
    assert next(iter(parsed["tpusnap_takes_total"]["samples"].values())) >= 1


# -------------------------------------------------------- overhead guard


def test_take_overhead_with_export_sinks_within_bound(tmp_path, metrics_env):
    """Acceptance: the ≤10% take-overhead guard still passes with BOTH
    export sinks enabled (prom rewrite + jsonl append per summary, sink
    span/counter callbacks inline on the recording threads)."""
    state = _state(total_bytes=16 << 20, n=8)

    def take_once(i, enabled):
        with override_telemetry_enabled(enabled), override_metrics_export(
            "prom,jsonl" if enabled else None
        ):
            t0 = time.perf_counter()
            Snapshot.take(
                str(tmp_path / f"s_{enabled}_{i}"), {"m": PytreeState(state)}
            )
            return time.perf_counter() - t0

    take_once(99, True)  # warmup: imports, native lib load, sink install
    runs = 5
    disabled = min(take_once(i, False) for i in range(runs))
    enabled = min(take_once(i, True) for i in range(runs))
    assert enabled <= disabled * 1.10 + 0.05, (
        f"telemetry+export overhead too high: enabled {enabled:.3f}s vs "
        f"disabled {disabled:.3f}s"
    )
