"""Chaos layer tests: deterministic fault injection + unified retry
middleware.

The fast seeds run in tier-1 (``chaos`` marker); the wide seed sweep is
``slow`` and excluded. Every end-to-end case asserts the two invariants
the robustness subsystem promises:

- the retry middleware CONVERGES: with ≥1 transient error injected per
  storage op (plus torn writes and short reads), take/restore/verify
  still succeed bit-exact through ``chaos+<scheme>://``;
- torn writes never corrupt a committed snapshot: whatever the fault
  schedule, a committed snapshot scrubs clean (``verify_snapshot``).
"""

import asyncio
import os

import numpy as np
import pytest

from tpusnap import (
    FaultPlan,
    InjectedFaultError,
    RetryPolicy,
    Snapshot,
    StateDict,
    verify_snapshot,
)
from tpusnap.faults import FaultInjectionStoragePlugin
from tpusnap.io_types import ReadIO, WriteIO
from tpusnap.retry import RetryingStoragePlugin, default_classify_transient
from tpusnap.storage_plugins.fs import FSStoragePlugin


def _run(coro):
    return asyncio.run(coro)


_FAST_OPTS = {"retry_backoff_base_sec": 0.01, "retry_backoff_cap_sec": 0.05}


def _chaos_opts(plan: FaultPlan) -> dict:
    return dict(_FAST_OPTS, fault_plan=plan)


def _state(seed: int, n_arrays: int = 5, size: int = 4096) -> dict:
    return {
        f"w{i}": np.random.default_rng(seed * 100 + i)
        .standard_normal(size)
        .astype(np.float32)
        for i in range(n_arrays)
    }


# --------------------------------------------------------------- FaultPlan


def test_fault_plan_spec_parsing():
    plan = FaultPlan.from_spec(
        "seed=3,transient_per_op=2,latency_ms=5,torn_writes=1,"
        "short_reads=1,crash_after_op=write:7"
    )
    assert plan.seed == 3
    assert plan.transient_per_op == 2
    assert abs(plan.latency_sec - 0.005) < 1e-9
    assert plan.torn_writes and plan.short_reads
    assert plan.crash_after_op == ("write", 7)
    assert FaultPlan.from_spec("bandwidth_gbps=0.25").bandwidth_gbps == 0.25
    with pytest.raises(ValueError, match="Unknown fault spec key"):
        FaultPlan.from_spec("bogus=1")


def test_fault_plan_coerce_env(monkeypatch):
    monkeypatch.setenv("TPUSNAP_FAULT_SPEC", "seed=9,transient_every=4")
    plan = FaultPlan.coerce(None)
    assert plan.seed == 9 and plan.transient_every == 4
    monkeypatch.delenv("TPUSNAP_FAULT_SPEC")
    assert FaultPlan.coerce(None).transient_per_op == 1  # default misbehaves
    assert FaultPlan.coerce({"seed": 2}).seed == 2
    same = FaultPlan(seed=5)
    assert FaultPlan.coerce(same) is same


def test_fault_plan_determinism(tmp_path):
    """Identical seeds inject identical fault schedules over a serial op
    sequence."""

    def fire_sequence(seed):
        plugin = FaultInjectionStoragePlugin(
            FSStoragePlugin(root=str(tmp_path / f"d{seed}")),
            FaultPlan(seed=seed, transient_every=3),
        )
        fired = []

        async def go():
            for i in range(12):
                try:
                    await plugin.write(WriteIO(path=f"o{i}", buf=b"x"))
                    fired.append(False)
                except InjectedFaultError:
                    fired.append(True)
            await plugin.close()

        _run(go())
        return fired

    a, b = fire_sequence(1), fire_sequence(1)
    assert a == b
    assert sum(a) == 4  # ops 3, 6, 9, 12 of 12


# ------------------------------------------------------------------ retry


class _FlakyPlugin(FSStoragePlugin):
    """Raises a configurable exception for the first N attempts per op."""

    def __init__(self, root, fail_times=1, exc_factory=None):
        super().__init__(root)
        self.fail_times = fail_times
        self.exc_factory = exc_factory or (
            lambda: ConnectionResetError("flaky")
        )
        self.attempts = {}

    def _maybe_fail(self, key):
        n = self.attempts.get(key, 0)
        self.attempts[key] = n + 1
        if n < self.fail_times:
            raise self.exc_factory()

    async def write(self, write_io):
        self._maybe_fail(("write", write_io.path))
        await super().write(write_io)

    async def read(self, read_io):
        self._maybe_fail(("read", read_io.path))
        await super().read(read_io)

    async def delete(self, path):
        self._maybe_fail(("delete", path))
        await super().delete(path)


def test_retrying_plugin_converges(tmp_path):
    inner = _FlakyPlugin(str(tmp_path), fail_times=2)
    plugin = RetryingStoragePlugin(
        inner, RetryPolicy(backoff_base_sec=0.01, backoff_cap_sec=0.02)
    )
    data = os.urandom(100_000)

    async def go():
        await plugin.write(WriteIO(path="a/b", buf=data))
        read_io = ReadIO(path="a/b")
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == data
        await plugin.delete("a/b")
        await plugin.close()

    _run(go())
    assert inner.attempts[("write", "a/b")] == 3  # 2 failures + success


def test_retrying_plugin_fatal_error_raises_immediately(tmp_path):
    inner = _FlakyPlugin(
        str(tmp_path),
        fail_times=100,
        exc_factory=lambda: PermissionError("denied"),
    )
    plugin = RetryingStoragePlugin(
        inner, RetryPolicy(backoff_base_sec=0.01)
    )
    with pytest.raises(PermissionError):
        _run(plugin.write(WriteIO(path="x", buf=b"data")))
    # one attempt only: PermissionError (EACCES-class) is not transient
    assert inner.attempts[("write", "x")] == 1


def test_retrying_plugin_deadline_expiry(tmp_path):
    inner = _FlakyPlugin(str(tmp_path), fail_times=10_000)
    plugin = RetryingStoragePlugin(
        inner,
        RetryPolicy(
            deadline_sec=0.2, backoff_base_sec=0.02, backoff_cap_sec=0.05
        ),
    )
    with pytest.raises(ConnectionResetError):
        _run(plugin.write(WriteIO(path="x", buf=b"data")))


def test_default_transient_classification():
    import errno as errno_mod

    assert default_classify_transient(ConnectionResetError("x"))
    assert default_classify_transient(TimeoutError("x"))
    assert default_classify_transient(InjectedFaultError("x"))
    assert default_classify_transient(
        OSError(errno_mod.EAGAIN, "again")
    )
    assert not default_classify_transient(OSError(errno_mod.ENOSPC, "full"))
    assert not default_classify_transient(ValueError("x"))
    assert not default_classify_transient(OSError("no errno"))

    class _Resp:
        status_code = 503

    class _HttpErr(Exception):
        response = _Resp()

    assert default_classify_transient(_HttpErr())


def test_retry_read_attempts_never_leak_torn_buffers(tmp_path):
    """A failing read that delivered partial bytes must not surface them:
    each retry attempt runs against a fresh ReadIO."""
    plugin = RetryingStoragePlugin(
        FaultInjectionStoragePlugin(
            FSStoragePlugin(root=str(tmp_path)),
            FaultPlan(seed=0, transient_per_op=1, short_reads=True),
        ),
        RetryPolicy(backoff_base_sec=0.01),
    )
    data = os.urandom(50_000)

    async def go():
        await plugin.write(WriteIO(path="blob", buf=data))
        read_io = ReadIO(path="blob")
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == data
        await plugin.close()

    _run(go())


# ------------------------------------------------------------- chaos e2e


def _chaos_roundtrip(url: str, opts: dict, seed: int) -> None:
    state = _state(seed)
    Snapshot.take(url, {"m": StateDict(**state)}, storage_options=opts)
    target = {"m": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    Snapshot(url, storage_options=opts).restore(target)
    for k, v in state.items():
        assert np.array_equal(target["m"][k], v), k
    report = verify_snapshot(url, storage_options=opts)
    assert report.clean, report


_FAST_CHAOS_SEEDS = [0, 1]
_SLOW_CHAOS_SEEDS = range(2, 12)


@pytest.mark.chaos
@pytest.mark.parametrize("seed", _FAST_CHAOS_SEEDS)
def test_chaos_fs_roundtrip(tmp_path, seed):
    """≥1 transient error per storage op + torn writes + short reads over
    chaos+fs://: the retry middleware converges and the committed
    snapshot is bit-exact and scrubs clean."""
    plan = FaultPlan(
        seed=seed, transient_per_op=1, torn_writes=True, short_reads=True
    )
    _chaos_roundtrip(
        f"chaos+fs://{tmp_path}/snap", _chaos_opts(plan), seed
    )


@pytest.mark.chaos
def test_chaos_fsspec_memory_roundtrip(tmp_path):
    plan = FaultPlan(seed=3, transient_per_op=1, short_reads=True)
    _chaos_roundtrip(
        "chaos+fsspec+memory://chaos_mem_snap", _chaos_opts(plan), 3
    )


@pytest.mark.chaos
def test_chaos_latency_and_every_n(tmp_path):
    """Latency injection and every-Nth-op faults compose with per-op
    transients."""
    plan = FaultPlan(
        seed=4,
        transient_per_op=1,
        transient_every=5,
        latency_sec=0.001,
        torn_writes=True,
    )
    _chaos_roundtrip(
        f"chaos+fs://{tmp_path}/snap", _chaos_opts(plan), 4
    )


def test_bandwidth_throttle_is_shared_across_concurrent_writes(tmp_path):
    """The write-path token bucket serializes payload bytes at the
    planned GB/s ACROSS concurrent ops (a per-op sleep would let N
    writers drain at N x the ceiling), and half the payload costs
    ~half the pipe time — the property the compression bench's
    compressed-vs-bypass legs measure against."""
    import time

    def timed_writes(nbytes_each, n_ops):
        plugin = FaultInjectionStoragePlugin(
            FSStoragePlugin(root=str(tmp_path / f"bw{nbytes_each}")),
            FaultPlan(bandwidth_gbps=0.05),  # 50 MB/s
        )
        payload = os.urandom(nbytes_each)

        async def go():
            t0 = time.monotonic()
            await asyncio.gather(
                *(
                    plugin.write(WriteIO(path=f"o{i}", buf=payload))
                    for i in range(n_ops)
                )
            )
            return time.monotonic() - t0

        return _run(go())

    full = timed_writes(1 << 20, 4)  # 4 MiB total at 50 MB/s >= ~80 ms
    assert full >= 0.9 * (4 * (1 << 20)) / 0.05e9
    half = timed_writes(1 << 19, 4)  # half the payload bytes
    assert half < full  # fewer bytes through the pipe = less wall


def test_bandwidth_throttled_snapshot_roundtrips(tmp_path):
    """A take through a throttled chaos URL commits and restores
    bit-exact; the throttle only costs wall time."""
    state = _state(seed=11, n_arrays=2)
    url = f"chaos+fs://{tmp_path}/snap"
    opts = _chaos_opts(
        FaultPlan(transient_per_op=0, bandwidth_gbps=0.5)
    )
    Snapshot.take(url, {"app": StateDict(**state)}, storage_options=opts)
    target = {
        "app": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})
    }
    Snapshot(url, storage_options=opts).restore(target)
    for k, v in state.items():
        np.testing.assert_array_equal(np.asarray(target["app"][k]), v)
    assert verify_snapshot(f"{tmp_path}/snap").clean


@pytest.mark.chaos
def test_chaos_s3_stub_ops(tmp_path):
    """The s3 plugin's ops converge under chaos through the retry
    middleware (stub client: aiobotocore is not installed here)."""
    from test_s3 import StubS3Client
    from tpusnap.storage_plugins.s3 import S3StoragePlugin

    raw = S3StoragePlugin("bucket/prefix")
    raw._client = StubS3Client()
    plugin = RetryingStoragePlugin(
        FaultInjectionStoragePlugin(
            raw,
            FaultPlan(seed=5, transient_per_op=1, short_reads=True),
        ),
        RetryPolicy(backoff_base_sec=0.01),
    )
    blobs = {f"o{i}": os.urandom(10_000 + i) for i in range(6)}

    async def go():
        await asyncio.gather(
            *(plugin.write(WriteIO(path=k, buf=v)) for k, v in blobs.items())
        )
        for k, v in blobs.items():
            read_io = ReadIO(path=k)
            await plugin.read(read_io)
            assert read_io.buf.getvalue() == v, k
        ranged = ReadIO(path="o0", byte_range=(100, 900))
        await plugin.read(ranged)
        assert ranged.buf.getvalue() == blobs["o0"][100:900]

    _run(go())


@pytest.mark.chaos
def test_chaos_incremental_dedup_survives_faults(tmp_path):
    """Incremental takes through a chaotic backend: dedup decisions and
    base references stay correct under injected faults."""
    from tpusnap.knobs import override_batching_disabled

    plan = FaultPlan(seed=6, transient_per_op=1, torn_writes=True)
    opts = _chaos_opts(plan)
    state = _state(6, n_arrays=3)
    with override_batching_disabled(True):
        Snapshot.take(
            f"chaos+fs://{tmp_path}/s0",
            {"m": StateDict(**state)},
            storage_options=opts,
        )
        Snapshot.take(
            f"chaos+fs://{tmp_path}/s1",
            {"m": StateDict(**state)},
            storage_options=opts,
            incremental_from=f"chaos+fs://{tmp_path}/s0",
        )
    target = {"m": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    Snapshot(f"chaos+fs://{tmp_path}/s1", storage_options=opts).restore(target)
    for k, v in state.items():
        assert np.array_equal(target["m"][k], v), k
    assert verify_snapshot(
        f"chaos+fs://{tmp_path}/s1", storage_options=opts
    ).clean


@pytest.mark.chaos
def test_chaos_transient_every_1_converges(tmp_path):
    """transient_every=1 fails every op's FIRST attempt; retries are
    exempt from the every-Nth draw, so the take still converges."""
    plan = FaultPlan(seed=8, transient_every=1)
    _chaos_roundtrip(f"chaos+fs://{tmp_path}/snap", _chaos_opts(plan), 8)


def test_progress_deadline_arms_lazily():
    """A plugin built long before its first op (async takes) must grant
    the first failing op a full retry window — the deadline starts at
    first consult, not construction."""
    from tpusnap.retry import ProgressDeadline

    deadline = ProgressDeadline(deadline_sec=0.0)  # instantly expirable
    # First consult arms the window and reports NOT expired even though
    # construction was arbitrarily long ago.
    assert not deadline.expired()


@pytest.mark.chaos
def test_chaos_async_take_roundtrip(tmp_path):
    """The background commit drain retries injected faults off the main
    thread; wait() returns a committed, clean snapshot."""
    plan = FaultPlan(seed=11, transient_per_op=1, torn_writes=True)
    opts = _chaos_opts(plan)
    url = f"chaos+fs://{tmp_path}/snap"
    state = _state(11)
    pending = Snapshot.async_take(
        url, {"m": StateDict(**state)}, storage_options=opts
    )
    snap = pending.wait()
    target = {"m": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    snap.restore(target)
    for k, v in state.items():
        assert np.array_equal(target["m"][k], v), k
    assert verify_snapshot(url, storage_options=opts).clean


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("seed", _SLOW_CHAOS_SEEDS)
def test_chaos_fs_roundtrip_seed_sweep(tmp_path, seed):
    """Wider seed sweep of the same invariants (excluded from tier-1)."""
    plan = FaultPlan(
        seed=seed,
        transient_per_op=1,
        transient_every=7,
        torn_writes=True,
        short_reads=True,
        latency_sec=0.001,
    )
    _chaos_roundtrip(
        f"chaos+fs://{tmp_path}/snap", _chaos_opts(plan), seed
    )
