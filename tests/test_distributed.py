"""Multi-process distributed tests: real jax.distributed worlds on CPU.

Mirrors the reference's pet-launcher distributed tests (tests/test_ddp.py,
tests/test_replication_glob.py, tests/test_dist_store.py,
tests/test_async_take.py) over the coordination-service substrate.
"""

import os
import tempfile

import pytest

from tpusnap.test_utils import run_subprocess_world

pytestmark = pytest.mark.distributed


# --- world functions (run inside jax.distributed-initialized subprocesses) --


def _world_collectives():
    import jax

    from tpusnap.comm import get_communicator

    comm = get_communicator()
    rank, world = comm.rank, comm.world_size
    assert world == int(os.environ["TPUSNAP_TEST_WORLD_SIZE"])

    gathered = comm.all_gather_object({"rank": rank, "payload": "x" * rank})
    assert [g["rank"] for g in gathered] == list(range(world))

    value = comm.broadcast_object(f"from-{rank}" if rank == 0 else None, src=0)
    assert value == "from-0"
    comm.barrier()


def _world_linear_barrier():
    from tpusnap.comm import get_communicator
    from tpusnap.dist_store import CoordinationKVStore, LinearBarrier

    comm = get_communicator()
    store = CoordinationKVStore()
    barrier = LinearBarrier(
        store, "test_lb", comm.rank, comm.world_size, timeout_sec=60
    )
    barrier.arrive()
    barrier.depart()


def _world_linear_barrier_error():
    from tpusnap.comm import get_communicator
    from tpusnap.dist_store import (
        CoordinationKVStore,
        LinearBarrier,
        LinearBarrierError,
    )

    comm = get_communicator()
    store = CoordinationKVStore()
    barrier = LinearBarrier(
        store, "test_lb_err", comm.rank, comm.world_size, timeout_sec=60
    )
    if comm.rank == 1:
        barrier.report_error(RuntimeError("rank1 exploded"))
    else:
        try:
            barrier.arrive()
            barrier.depart()
        except LinearBarrierError as e:
            assert "rank1 exploded" in str(e)
        else:
            raise AssertionError("leader did not observe the reported error")


def _world_replicated_take_restore(snap_dir):
    import jax.numpy as jnp
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    # Same logical value on every rank (DDP-style), replicated via glob.
    state = StateDict(
        w=jnp.arange(256, dtype=jnp.float32).reshape(16, 16),
        b=jnp.ones(16, dtype=jnp.float32) * 3,
        step=42,
    )
    snap = Snapshot.take(snap_dir, {"model": state}, replicated=["**"])

    manifest = snap.get_manifest()
    # Replicated entries consolidated into rank 0's tree only.
    assert "0/model/w" in manifest
    assert "1/model/w" not in manifest
    assert manifest["0/model/w"].replicated

    dst = {
        "model": StateDict(
            w=jnp.zeros((16, 16), jnp.float32), b=jnp.zeros(16, jnp.float32), step=0
        )
    }
    Snapshot(snap_dir).restore(dst)
    assert dst["model"]["step"] == 42
    np.testing.assert_array_equal(np.asarray(dst["model"]["w"]), np.asarray(state["w"]))
    np.testing.assert_array_equal(np.asarray(dst["model"]["b"]), np.asarray(state["b"]))


def _world_partitioner_spreads_writes(snap_dir):
    import jax.numpy as jnp

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.knobs import override_batching_disabled

    comm = get_communicator()
    state = StateDict(
        **{f"p{i}": jnp.full((64,), i, jnp.float32) for i in range(8)}
    )
    with override_batching_disabled(True):
        Snapshot.take(snap_dir, {"m": state}, replicated=["**"])
    if comm.rank == 0:
        # All 8 replicated blobs exist under replicated/ exactly once;
        # the greedy partitioner must have spread them across both ranks'
        # write loads (we can't observe who wrote, but all must exist).
        files = os.listdir(os.path.join(snap_dir, "replicated", "m"))
        assert len(files) == 8, files


def _world_global_mesh_sharded(snap_dir):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.manifest import ShardedEntry

    comm = get_communicator()
    # Global mesh spanning both processes (2 procs × 2 devices = 4).
    devices = np.array(jax.devices()).reshape(4)
    mesh = Mesh(devices, ("x",))
    sharding = NamedSharding(mesh, P("x"))

    global_shape = (8, 4)
    # Build the global array from per-process local shards.
    arr = jax.make_array_from_callback(
        global_shape,
        sharding,
        lambda idx: np.arange(32, dtype=np.float32).reshape(global_shape)[idx],
    )
    assert not arr.is_fully_addressable

    snap = Snapshot.take(snap_dir, {"s": StateDict(a=arr)})
    entry = snap.get_manifest().get("0/s/a") or snap.get_manifest().get("1/s/a")
    assert entry is not None, "sharded entry missing from gathered manifest"

    # Restore into the same global sharding.
    dst_arr = jax.make_array_from_callback(
        global_shape, sharding, lambda idx: np.zeros(global_shape, np.float32)[idx]
    )
    dst = {"s": StateDict(a=dst_arr)}
    Snapshot(snap_dir).restore(dst)
    out = dst["s"]["a"]
    # Each process checks its addressable shards.
    expected = np.arange(32, dtype=np.float32).reshape(global_shape)
    for shard in out.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), expected[shard.index])

    # Manifest: 4 shards total across both ranks' entries, no duplicates.
    manifest = Snapshot(snap_dir).metadata.manifest
    all_shards = []
    for key, e in manifest.items():
        if isinstance(e, ShardedEntry):
            all_shards.extend(tuple(s.offsets) for s in e.shards)
    assert sorted(all_shards) == [(0, 0), (2, 0), (4, 0), (6, 0)]


def _world_async_take_fault(snap_dir):
    import jax.numpy as jnp

    import tpusnap.storage_plugin as sp
    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    comm = get_communicator()

    class FaultyFS(FSStoragePlugin):
        async def write(self, write_io):
            if comm.rank == 1 and not write_io.path.endswith(".snapshot_metadata"):
                raise OSError("rank1 disk failure")
            await super().write(write_io)

    orig = sp.url_to_storage_plugin
    sp.url_to_storage_plugin = lambda url, storage_options=None: FaultyFS(
        root=url.split("://")[-1]
    )
    try:
        pending = Snapshot.async_take(snap_dir, {"s": StateDict(x=jnp.ones(128))})
        try:
            pending.wait()
            raised = False
        except Exception:
            raised = True
        # Critical invariant (reference tests/test_async_take.py:25-64):
        # on ANY rank's failure, .snapshot_metadata must never be written.
        assert not os.path.exists(os.path.join(snap_dir, ".snapshot_metadata"))
        if comm.rank == 1:
            assert raised, "failing rank must re-raise from wait()"
        else:
            assert raised, "peer rank must observe the poisoned barrier"
    finally:
        sp.url_to_storage_plugin = orig


def _world_async_take_happy(snap_dir):
    """async_take → training mutates state in place → wait(): the snapshot
    must hold the PRE-mutation values under real process parallelism, in
    BOTH staging modes (reference tests/test_async_take.py happy path +
    io_preparers/tensor.py:281-305). Default (COW) mode: live bytes back
    the in-flight writes, so training mutates after the wait_staged()
    rendezvous. TPUSNAP_ASYNC_COW=0: the defensive clone froze the
    content, so training mutates immediately. A slow storage plugin
    guarantees the mutation lands while storage I/O is still in flight."""
    import asyncio
    import os

    import numpy as np

    import tpusnap.storage_plugin as sp
    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    comm = get_communicator()

    class SlowFS(FSStoragePlugin):
        async def write(self, write_io):
            await asyncio.sleep(1.0)
            await super().write(write_io)

    orig = sp.url_to_storage_plugin
    sp.url_to_storage_plugin = lambda url, storage_options=None: SlowFS(
        root=url.split("://")[-1]
    )
    try:
        for leg, cow in (("cow", True), ("clone", False)):
            os.environ["TPUSNAP_ASYNC_COW"] = "1" if cow else "0"
            path = f"{snap_dir}_{leg}"
            state = StateDict(
                w=np.full((1024,), float(comm.rank), dtype=np.float32),
                step=0,
            )
            pending = Snapshot.async_take(path, {"s": state})
            assert not pending.done()
            if cow:
                # COW-aware rendezvous: safe to mutate only after THIS
                # RANK's writes drained (the commit barrier may still be
                # pending — done() can be False while staged() is True).
                assert pending.wait_staged(timeout=60.0)
            # "Training step": mutate the live arrays while the commit
            # (and in clone mode the storage I/O itself) is in flight.
            state["w"] += 1000.0
            state["step"] = 99
            pending.wait()

            target = {
                "s": StateDict(w=np.zeros(1024, dtype=np.float32), step=-1)
            }
            Snapshot(path).restore(target)
            np.testing.assert_array_equal(
                np.asarray(target["s"]["w"]),
                np.full((1024,), float(comm.rank), dtype=np.float32),
            )
            assert target["s"]["step"] == 0
    finally:
        sp.url_to_storage_plugin = orig
        os.environ.pop("TPUSNAP_ASYNC_COW", None)


def _world_elastic_restore(snap_dir, phase):
    import jax.numpy as jnp
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    if phase == "save":  # world_size 2
        state = StateDict(
            shared=jnp.arange(64, dtype=jnp.float32),
            own=jnp.full((4,), float(comm.rank)),
        )
        Snapshot.take(snap_dir, {"m": state}, replicated=["m/shared"])
    else:  # world_size 3: rank 2 is new
        dst = {
            "m": StateDict(
                shared=jnp.zeros(64, jnp.float32), own=jnp.full((4,), -1.0)
            )
        }
        Snapshot(snap_dir).restore(dst)
        np.testing.assert_array_equal(
            np.asarray(dst["m"]["shared"]), np.arange(64, dtype=np.float32)
        )
        if comm.rank < 2:
            np.testing.assert_array_equal(
                np.asarray(dst["m"]["own"]), np.full((4,), float(comm.rank))
            )
        else:
            # New rank: no per-rank entry exists for it, so the key is
            # absent from the restored dict (manifest is the source of
            # truth — reference manifest_ops.py:74-84 semantics).
            assert "own" not in dst["m"]


# --- pytest wrappers --------------------------------------------------------


def test_comm_collectives():
    run_subprocess_world(_world_collectives, world_size=2)


def test_comm_collectives_world3():
    run_subprocess_world(_world_collectives, world_size=3)


def test_linear_barrier():
    run_subprocess_world(_world_linear_barrier, world_size=2)


def test_linear_barrier_error_propagation():
    run_subprocess_world(_world_linear_barrier_error, world_size=2)


def test_replicated_take_restore():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_replicated_take_restore, world_size=2, args=[f"{d}/snap"]
        )


def test_partitioner_spreads_writes():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_partitioner_spreads_writes, world_size=2, args=[f"{d}/snap"]
        )


def test_global_mesh_sharded_take_restore():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_global_mesh_sharded, world_size=2, args=[f"{d}/snap"]
        )


def test_async_take_fault_never_commits():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_async_take_fault, world_size=2, args=[f"{d}/snap"]
        )


def test_async_take_happy_path_consistent_under_mutation():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_async_take_happy, world_size=2, args=[f"{d}/snap"]
        )


def test_elastic_upscale_restore():
    """Save with world 2, restore with world 3 (reference
    tests/test_ddp.py:81-133 upscale elasticity)."""
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_elastic_restore, world_size=2, args=[f"{d}/snap", "save"]
        )
        run_subprocess_world(
            _world_elastic_restore, world_size=3, args=[f"{d}/snap", "restore"]
        )


def _world_collective_count(snap_dir):
    """Assert take's coalesced collective structure: exactly 2 gathers
    (pre-staging coalesce + manifest) + 2 barriers (two-phase commit),
    NO broadcasts; restore and read_object issue ZERO collectives here
    because take's gather already cached the memory-budget divisor in
    this process (a cold restore in a fresh process pays exactly one
    hostname gather)."""
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import Communicator, get_communicator

    class CountingComm(Communicator):
        def __init__(self, inner):
            self.inner = inner
            self.counts = {"barrier": 0, "all_gather": 0, "broadcast": 0}

        @property
        def rank(self):
            return self.inner.rank

        @property
        def world_size(self):
            return self.inner.world_size

        def barrier(self):
            self.counts["barrier"] += 1
            self.inner.barrier()

        def all_gather_object(self, obj):
            self.counts["all_gather"] += 1
            return self.inner.all_gather_object(obj)

        def broadcast_object(self, obj, src=0):
            self.counts["broadcast"] += 1
            return self.inner.broadcast_object(obj, src)

    comm = CountingComm(get_communicator())
    state = StateDict(
        w=np.arange(4096, dtype=np.float32),
        b=np.ones(64, dtype=np.float32) * comm.rank,
        step=7,
    )
    Snapshot.take(snap_dir, {"m": state}, replicated=["m/w"], comm=comm)
    assert comm.counts == {"barrier": 2, "all_gather": 2, "broadcast": 0}, (
        comm.counts
    )

    restore_comm = CountingComm(get_communicator())
    dst = {
        "m": StateDict(
            w=np.zeros(4096, np.float32), b=np.zeros(64, np.float32), step=0
        )
    }
    Snapshot(snap_dir, comm=restore_comm).restore(dst)
    assert restore_comm.counts == {
        "barrier": 0,
        "all_gather": 0,
        "broadcast": 0,
    }, restore_comm.counts
    assert dst["m"]["step"] == 7
    np.testing.assert_array_equal(dst["m"]["b"], np.ones(64) * comm.rank)

    out = Snapshot(snap_dir, comm=restore_comm).read_object("0/m/w")
    np.testing.assert_array_equal(out, np.arange(4096, dtype=np.float32))
    assert restore_comm.counts["all_gather"] == 0, restore_comm.counts

    # per_key_barrier=True restores the reference's safety mode: one
    # extra key gather + one barrier per key.
    safety_comm = CountingComm(get_communicator())
    Snapshot.take(
        f"{snap_dir}_pkb",
        {"m": state},
        replicated=["m/w"],
        comm=safety_comm,
        per_key_barrier=True,
    )
    assert safety_comm.counts["all_gather"] == 3, safety_comm.counts
    assert safety_comm.counts["barrier"] == 3, safety_comm.counts


def test_collective_count_world8():
    """World-8: the coalesced comm structure holds at (modest) scale and
    each collective is O(1) KV RPCs per rank (one set + one barrier +
    one dir-get), so take cost no longer grows with world size."""
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_collective_count,
            world_size=8,
            devices_per_process=1,
            args=[f"{d}/snap"],
        )


def _world_interleaved_communicators():
    """Two Communicator instances used in DIFFERENT relative orders on
    different ranks must not cross-wire values (the process-global
    sequence this replaces silently swapped payloads here)."""
    from tpusnap.comm import JaxCoordinationComm, get_communicator

    base = get_communicator()
    rank = base.rank
    comm_a = JaxCoordinationComm(namespace="test_a")
    comm_b = JaxCoordinationComm(namespace="test_b")

    if rank == 0:
        # A first, then B.
        comm_a.broadcast_object("from-A", src=0)
        comm_b.broadcast_object("from-B", src=0)
    else:
        # B first, then A — divergent cross-instance order.
        got_b = comm_b.broadcast_object(None, src=0)
        got_a = comm_a.broadcast_object(None, src=0)
        assert got_b == "from-B", got_b
        assert got_a == "from-A", got_a
    base.barrier()

    # Interleaved gathers on both instances still route correctly.
    ga = comm_a.all_gather_object(("a", rank))
    gb = comm_b.all_gather_object(("b", rank * 10))
    assert ga == [("a", r) for r in range(base.world_size)], ga
    assert gb == [("b", r * 10) for r in range(base.world_size)], gb


def test_interleaved_communicator_instances():
    run_subprocess_world(
        _world_interleaved_communicators, world_size=2, devices_per_process=1
    )


def test_comm_collectives_world16():
    """The O(1)-RPC comm design at world 16: gathers/broadcasts/barriers
    complete promptly (serial-RPC designs degrade quadratically here)."""
    run_subprocess_world(
        _world_collectives, world_size=16, devices_per_process=1, timeout=480
    )


def _world_overlapping_async_takes(snap_dir):
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    rng = np.random.default_rng(comm.rank)

    def state(step):
        return StateDict(
            local=rng.standard_normal((256, 32)).astype(np.float32) + step,
            step=step,
        )

    # Three async takes launched back-to-back WITHOUT waiting between
    # them: multiple PendingSnapshots in flight on one communicator
    # (distinct KV barriers; epoch-bounded GC must not release a newer
    # take's in-flight keys).
    pendings = []
    states = []
    for step in range(3):
        st = state(step)
        states.append(st)
        pendings.append(
            Snapshot.async_take(f"{snap_dir}/s{step}", {"app": st})
        )
    snaps = [p.wait() for p in pendings]
    for step, snap in enumerate(snaps):
        assert snap.metadata.world_size == comm.world_size
    if comm.rank == 0:
        for step in range(3):
            assert verify_snapshot(f"{snap_dir}/s{step}").clean, step
    # Restore the newest on every rank; rank-local content round-trips.
    target = {"app": StateDict(local=np.zeros((256, 32), np.float32), step=-1)}
    Snapshot(f"{snap_dir}/s2").restore(target)
    assert target["app"]["step"] == 2
    np.testing.assert_array_equal(target["app"]["local"], states[2]["local"])


def test_overlapping_async_takes():
    """Back-to-back async_takes with all commits in flight at once."""
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_overlapping_async_takes, world_size=2, args=[f"{d}/snap"]
        )


def _world_tile_grain_incremental(snap_dir):
    """World-2 incremental chain mixing per-rank dense state (tile-grain
    dedup active), replicated state (tile route DISABLED in multi —
    the write-load estimator's unit ids must stay blob-grain on every
    rank), and sharded state (blob-grain shard dedup)."""
    import numpy as np

    import jax

    from tpusnap import PytreeState, Snapshot, StateDict, verify_snapshot
    from tpusnap.comm import get_communicator
    from tpusnap.knobs import (
        override_record_dedup_hashes,
        override_tile_checksum_bytes,
    )

    comm = get_communicator()
    rank = comm.rank

    def state(step):
        # per-rank dense (1024, 64) f32 = 256 KiB -> 4 KiB tiles
        local = (
            np.arange(1024 * 64, dtype=np.float32).reshape(1024, 64)
            + rank * 1000
        )
        if step:
            local = local.copy()
            local[500, :] += step  # one row -> one tile
        repl = np.full((2048,), 7.0, np.float32)  # identical on all ranks
        if step:
            repl = repl + step
        return StateDict(local=local, repl=repl, step=step)

    with override_tile_checksum_bytes(4 * 1024), override_record_dedup_hashes(
        True
    ):
        Snapshot.take(
            f"{snap_dir}/s0", {"app": state(0)}, replicated=["app/repl"]
        )
        comm.barrier()
        Snapshot.take(
            f"{snap_dir}/s1",
            {"app": state(1)},
            replicated=["app/repl"],
            incremental_from=f"{snap_dir}/s0",
        )
    comm.barrier()
    if rank == 0:
        # Each rank's dense blob wrote ~one 4 KiB tile, not 256 KiB;
        # repl rewrote whole (tile route off for multi replicated).
        total = 0
        for dirpath, _, files in os.walk(f"{snap_dir}/s1"):
            if ".tpusnap" in dirpath.split(os.sep):
                continue
            for f in files:
                if f != ".snapshot_metadata":
                    total += os.path.getsize(os.path.join(dirpath, f))
        assert total < 64 * 1024, f"s1 wrote {total} bytes"
        assert verify_snapshot(f"{snap_dir}/s1").clean
    comm.barrier()
    target = {
        "app": StateDict(
            local=np.zeros((1024, 64), np.float32),
            repl=np.zeros((2048,), np.float32),
            step=-1,
        )
    }
    Snapshot(f"{snap_dir}/s1").restore(target)
    expect = state(1)
    np.testing.assert_array_equal(target["app"]["local"], expect["local"])
    np.testing.assert_array_equal(target["app"]["repl"], expect["repl"])
    assert target["app"]["step"] == 1


def test_tile_grain_incremental_world2():
    """Tile-grain dedup in a real 2-process world: per-rank tiles skip,
    replicated entries stay blob-grain (no estimator drift), restore and
    scrub resolve the mixed form."""
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_tile_grain_incremental, world_size=2, args=[f"{d}/snap"]
        )


def _world_durable_commit(snap_dir):
    """TPUSNAP_DURABLE_COMMIT in a 2-process world: every rank flushes
    its own created dirents before the commit barrier; the committed
    snapshot restores and scrubs on both ranks."""
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.comm import get_communicator

    os.environ["TPUSNAP_DURABLE_COMMIT"] = "1"
    comm = get_communicator()
    rank = comm.rank
    local = np.arange(4096, dtype=np.float32) + rank
    Snapshot.take(f"{snap_dir}/s0", {"app": StateDict(local=local)})
    # async path exercises the background-thread flush too
    Snapshot.async_take(f"{snap_dir}/s1", {"app": StateDict(local=local)}).wait()
    comm.barrier()
    for s in ("s0", "s1"):
        target = {"app": StateDict(local=np.zeros(4096, np.float32))}
        Snapshot(f"{snap_dir}/{s}").restore(target)
        np.testing.assert_array_equal(target["app"]["local"], local)
    if rank == 0:
        assert verify_snapshot(f"{snap_dir}/s0").clean
        assert verify_snapshot(f"{snap_dir}/s1").clean


def test_durable_commit_world2():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_durable_commit, world_size=2, args=[f"{d}/snap"]
        )


def _world_multihost_budget(snap_dir):
    """4 ranks across 2 simulated hosts: the per-host memory-budget
    divisor must see local_world_size == 2 (ranks sharing MY node), and
    the write-load partitioner must keep spreading replicated entries
    across ALL ranks regardless of host boundaries (reference
    benchmarks/ddp/README.md scales 1x8 -> 4x8 across nodes; spread is
    per-rank there too)."""
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap import scheduler as sched
    from tpusnap.comm import get_communicator

    from tpusnap.knobs import override_batching_disabled

    comm = get_communicator()
    state = StateDict(
        **{
            f"w{i}": np.arange(256 * 64, dtype=np.float32).reshape(256, 64)
            + i
            for i in range(8)
        }
    )
    with override_batching_disabled(True):
        Snapshot.take(snap_dir, {"model": state}, replicated=["**"])

    # G1's hostname gather threaded the simulated topology into the
    # budget divisor: 2 ranks per simulated host.
    assert sched._cached_local_world_size == 2, (
        comm.rank,
        sched._cached_local_world_size,
    )

    if comm.rank == 0:
        # Every replicated blob exists exactly once.
        files = os.listdir(os.path.join(snap_dir, "replicated", "model"))
        assert len(files) == 8, files
        # The partitioner's assignment is HOST-AGNOSTIC: fed the same
        # per-rank unit estimates take gathered, it spreads the 8 equal
        # units across ranks on BOTH simulated hosts.
        from tpusnap.partitioner import (
            assign_replicated_units,
            estimate_write_loads,
        )

        flattened = {
            f"model/w{i}": state[f"w{i}"] for i in range(8)
        }
        units, base_load, _ = estimate_write_loads(
            flattened, sorted(flattened)
        )
        assignment, _ = assign_replicated_units(
            [units] * 4, [base_load] * 4
        )
        writer_ranks = set(assignment.values())
        assert len(writer_ranks) >= 2, assignment
        assert writer_ranks & {0, 1} and writer_ranks & {2, 3}, assignment
    # Restore round-trips under the same simulated topology.
    target = {"model": StateDict(**{f"w{i}": np.zeros((256, 64), np.float32) for i in range(8)})}
    Snapshot(snap_dir).restore(target)
    for i in range(8):
        assert np.array_equal(
            target["model"][f"w{i}"],
            np.arange(256 * 64, dtype=np.float32).reshape(256, 64) + i,
        )


def test_multihost_simulated_budget_divisor():
    """VERDICT r4 #5: 4 ranks / 2 simulated hosts — the memory-budget
    divisor runs with local_world_size == 2 derived from heterogeneous
    node names, and the partitioner spread is unchanged."""
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_multihost_budget,
            world_size=4,
            args=[f"{d}/snap"],
            hostnames=["hostA", "hostA", "hostB", "hostB"],
        )


def _world_late_checksums(snap_dir):
    """Multi-process deferred checksums: the committed metadata carries
    every rank's checksums (hashed on the write path, transported via
    the commit barrier's KV store), EVERY rank's returned handle caches
    a fully-patched metadata (non-leaders apply the same KV patch to
    their local copies — ADVICE r5 #4 — instead of re-reading the
    committed file), and the take-scoped KV keys are DELETED after the
    final barrier — one leaked blob per rank per take would grow the
    coordination service for the job's lifetime."""
    import numpy as np

    import tpusnap.snapshot as snap_mod
    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.snapshot import _get_kv_store

    # The deferral path actually ENGAGED: a regression to eager hashing
    # would make every later assertion here pass vacuously, so count
    # the KV publishes the deferral transport performs.
    publishes = []
    orig_publish = snap_mod._LateChecksums.publish

    def counting_publish(self):
        publishes.append(1)
        return orig_publish(self)

    snap_mod._LateChecksums.publish = counting_publish

    comm = get_communicator()
    rank = comm.rank
    state = StateDict(
        w=np.arange(512 * 64, dtype=np.float32).reshape(512, 64) + rank,
        small=np.ones(32, np.float32) * rank,
    )
    snap = Snapshot.take(snap_dir, {"app": state})
    assert publishes, "late-checksum deferral did not engage"
    # Every rank — leader or not — caches fully-patched metadata: the
    # non-leader's IN-MEMORY manifest carries every rank's checksums
    # without a metadata GET (its cached copy was patched from the KV).
    assert snap._metadata is not None, rank
    for key in (f"{r}/app/w" for r in range(comm.world_size)):
        assert snap._metadata.manifest[key].checksum is not None, (rank, key)
    # Every rank's handle verifies clean.
    report = snap.verify()
    assert report.clean, (rank, report.summary())
    manifest = Snapshot(snap_dir).metadata.manifest
    for key in (f"{r}/app/w" for r in range(comm.world_size)):
        assert manifest[key].checksum is not None, key
    # The late-checksum KV keys were cleaned up by rank 0 after the
    # final barrier (every rank had read them by then).
    comm.barrier()
    store = _get_kv_store(comm)
    leftovers = store.try_get_dir("tpusnap_late_cs/")
    # None would mean the listing itself failed — the leak check must
    # OBSERVE an empty directory, not fail to look.
    assert leftovers is not None and not leftovers, leftovers

    # Async path: same properties.
    pending = Snapshot.async_take(snap_dir + "_a", {"app": state})
    snap2 = pending.wait()
    assert snap2._metadata is not None, rank
    for key in (f"{r}/app/w" for r in range(comm.world_size)):
        assert snap2._metadata.manifest[key].checksum is not None, (rank, key)
    assert snap2.verify().clean, rank
    comm.barrier()
    leftovers = store.try_get_dir("tpusnap_late_cs/")
    assert leftovers is not None and not leftovers, leftovers


def test_late_checksums_world2():
    with tempfile.TemporaryDirectory() as d:
        run_subprocess_world(
            _world_late_checksums, world_size=2, args=[f"{d}/snap"]
        )
