"""Test configuration: force an 8-device CPU platform so sharding tests can
exercise real multi-device meshes without TPU hardware (the driver dry-runs
the multi-chip path the same way)."""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(params=[False, True], ids=["batching_on", "batching_off"])
def toggle_batching(request):
    """Run a test under both batching modes (reference tests/conftest.py:15-18)."""
    from tpusnap.knobs import override_batching_disabled

    with override_batching_disabled(request.param):
        yield request.param
