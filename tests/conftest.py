"""Test configuration: force an 8-device CPU platform so sharding tests can
exercise real multi-device meshes without TPU hardware (the driver dry-runs
the multi-chip path the same way).

The environment pre-sets PYTHONPATH=/root/.axon_site whose sitecustomize
registers the real-TPU "axon" backend at interpreter startup, so plain
JAX_PLATFORMS env assignment is too late — but jax.config.update still
works as long as no devices have been queried yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn
# Run the whole suite under the lock-order watchdog (set before any
# test imports tpusnap — the package auto-installs the instrumentation
# at import when this is on), so tier-1 doubles as a deadlock detector.
# pytest_sessionfinish below fails the session on any reported cycle.
# Override with TPUSNAP_LOCKCHECK=0 to measure the uninstrumented suite.
os.environ.setdefault("TPUSNAP_LOCKCHECK", "1")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # Newer JAX spells the device-count override as a config option; on
    # older versions the XLA_FLAGS set above already did the job.
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import pytest  # noqa: E402


@pytest.fixture(params=[False, True], ids=["batching_on", "batching_off"])
def toggle_batching(request):
    """Run a test under both batching modes (reference tests/conftest.py:15-18)."""
    from tpusnap.knobs import override_batching_disabled

    with override_batching_disabled(request.param):
        yield request.param


def pytest_sessionfinish(session, exitstatus):
    """Lock-order gate: the whole suite ran under TPUSNAP_LOCKCHECK=1
    (unless explicitly disabled); any AB/BA cycle in the accumulated
    lock-order graph is a potential deadlock and fails the session —
    the PR 6 tier-1 hang, caught as a report instead of a timeout."""
    try:
        from tpusnap.devtools import lockwatch
    except Exception:
        return
    watch = lockwatch.active_watch()
    if watch is None:
        return
    report = watch.render()
    print(f"\n{report}")
    if watch.cycles():
        print(
            "lockwatch: lock-order cycle(s) detected during the test "
            "session — failing the run (see the cycle report above)"
        )
        session.exitstatus = 1
