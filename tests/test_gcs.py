"""GCS plugin tests against a local fake HTTP server.

The reference gates its GCS tests behind a real bucket
(/root/reference/tests/test_gcs_storage_plugin.py); here a fake server
exercises the subtle paths deterministically, with fault injection:
resumable-upload chunking, 308 short-Range persistence forcing the
``bytes */total`` offset resync, 308-without-Range (no progress) retry,
transient-500 retry, collective-deadline expiry, and chunked ranged
download reassembly.
"""

import asyncio
import io
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import tpusnap.storage_plugins.gcs as gcs_mod
from tpusnap.io_types import ReadIO, WriteIO
from tpusnap.storage_plugins.gcs import GCSStoragePlugin


class FakeGCS:
    """In-memory GCS fake speaking the JSON/upload API subset the plugin
    uses. Fault injection via the ``faults`` list: each entry is a dict
    consumed (in order) by the matching request kind:
      {"kind": "chunk", "action": "http500"}
      {"kind": "chunk", "action": "short", "keep": <bytes_of_this_chunk>}
      {"kind": "chunk", "action": "no_progress"}  # 308 without Range
      {"kind": "download", "action": "http500"}
    """

    def __init__(self):
        self.objects = {}
        self.sessions = {}  # sid -> {"name":, "data": bytearray, "total": int}
        self.faults = []
        self.request_log = []
        # Injected per-request latency (seconds) — simulates cloud RTT;
        # ThreadingHTTPServer handles each request on its own thread, so
        # concurrent plugin requests overlap their sleeps and the
        # benchmarks/gcs_pipeline harness can measure pipeline
        # concurrency as sum(latency)/wall.
        self.latency_s = 0.0
        self._next_sid = 0
        self._lock = threading.Lock()

    def pop_fault(self, kind):
        with self._lock:
            for i, f in enumerate(self.faults):
                if f["kind"] == kind:
                    return self.faults.pop(i)
        return None


def _make_handler(state: FakeGCS):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):  # silence
            pass

        def _reply(self, code, headers=None, body=b""):
            if state.latency_s:
                import time as _time

                _time.sleep(state.latency_s)
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def _read_body(self):
            n = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(n) if n else b""

        def do_POST(self):
            state.request_log.append(("POST", self.path))
            body = self._read_body()
            m = re.match(r"/upload/storage/v1/b/([^/]+)/o\?uploadType=(\w+)&name=(.*)", self.path)
            if not m:
                return self._reply(404)
            from urllib.parse import unquote

            kind, name = m.group(2), unquote(m.group(3))
            if kind == "resumable":
                with state._lock:
                    sid = str(state._next_sid)
                    state._next_sid += 1
                    state.sessions[sid] = {
                        "name": name,
                        "data": bytearray(),
                    }
                host = self.headers["Host"]
                return self._reply(
                    200, {"Location": f"http://{host}/upload-session/{sid}"}
                )
            if kind == "media":
                state.objects[name] = bytes(body)
                return self._reply(200, body=b"{}")
            return self._reply(404)

        def do_PUT(self):
            state.request_log.append(("PUT", self.path, self.headers.get("Content-Range")))
            body = self._read_body()
            m = re.match(r"/upload-session/(\w+)", self.path)
            if not m:
                return self._reply(404)
            sess = state.sessions.get(m.group(1))
            if sess is None:
                return self._reply(404)
            crange = self.headers.get("Content-Range", "")
            probe = re.match(r"bytes \*/(\d+)", crange)
            if probe:
                # Status query: report persisted bytes. Never a fault target
                # (the plugin relies on it to resynchronize).
                persisted = len(sess["data"])
                if persisted and persisted == int(probe.group(1)):
                    state.objects[sess["name"]] = bytes(sess["data"])
                    return self._reply(200, body=b"{}")
                headers = (
                    {"Range": f"bytes=0-{persisted - 1}"} if persisted else {}
                )
                return self._reply(308, headers)
            m2 = re.match(r"bytes (\d+)-(\d+)/(\d+)", crange)
            if not m2:
                return self._reply(400)
            start, end, total = int(m2.group(1)), int(m2.group(2)), int(m2.group(3))
            fault = state.pop_fault("chunk")
            if fault:
                if fault["action"] == "http500":
                    return self._reply(500)
                if fault["action"] == "no_progress":
                    persisted = len(sess["data"])
                    headers = (
                        {"Range": f"bytes=0-{persisted - 1}"} if persisted else {}
                    )
                    # A stale header reporting no NEW progress; with zero
                    # persisted, omit Range entirely (the rawest form).
                    return self._reply(308, headers)
                if fault["action"] == "short":
                    keep = fault["keep"]
                    if start != len(sess["data"]):
                        return self._reply(503)
                    sess["data"].extend(body[:keep])
                    persisted = len(sess["data"])
                    headers = (
                        {"Range": f"bytes=0-{persisted - 1}"} if persisted else {}
                    )
                    return self._reply(308, headers)
            if start != len(sess["data"]):
                # Offset mismatch — the client must resync via a probe.
                return self._reply(503)
            sess["data"].extend(body)
            if end + 1 == total and len(sess["data"]) == total:
                state.objects[sess["name"]] = bytes(sess["data"])
                return self._reply(200, body=b"{}")
            return self._reply(308, {"Range": f"bytes=0-{len(sess['data']) - 1}"})

        def do_GET(self):
            state.request_log.append(("GET", self.path, self.headers.get("Range")))
            from urllib.parse import unquote

            m = re.match(r"/storage/v1/b/([^/]+)/o/([^?]+)(\?alt=media)?$", self.path)
            if not m:
                return self._reply(404)
            name = unquote(m.group(2))
            if name not in state.objects:
                return self._reply(404)
            data = state.objects[name]
            if m.group(3):  # media download
                fault = state.pop_fault("download")
                if fault and fault["action"] == "http500":
                    return self._reply(500)
                rng = self.headers.get("Range")
                if rng:
                    rm = re.match(r"bytes=(\d+)-(\d+)", rng)
                    lo, hi = int(rm.group(1)), int(rm.group(2))
                    return self._reply(206, body=data[lo : hi + 1])
                return self._reply(200, body=data)
            return self._reply(
                200, body=json.dumps({"size": len(data)}).encode()
            )

        def do_DELETE(self):
            from urllib.parse import unquote

            m = re.match(r"/storage/v1/b/([^/]+)/o/([^?]+)$", self.path)
            name = unquote(m.group(2))
            if name in state.objects:
                del state.objects[name]
                return self._reply(204)
            return self._reply(404)

    return Handler


@pytest.fixture()
def fake_gcs():
    state = FakeGCS()
    server = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(state))
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    state.endpoint = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        yield state
    finally:
        server.shutdown()
        thread.join(timeout=5)


def _plugin(state, **options):
    opts = {"api_endpoint": state.endpoint, "deadline_sec": 30.0}
    opts.update(options)
    return GCSStoragePlugin("bkt/prefix", storage_options=opts)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_round_trip_multi_chunk(fake_gcs, monkeypatch):
    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 1000)
    plugin = _plugin(fake_gcs)
    payload = bytes(range(256)) * 20  # 5120 bytes -> 6 chunks
    _run(plugin.write(WriteIO(path="obj", buf=memoryview(payload))))
    assert fake_gcs.objects["prefix/obj"] == payload
    read_io = ReadIO(path="obj")
    _run(plugin.read(read_io))
    assert read_io.buf.getvalue() == payload
    _run(plugin.delete("obj"))
    assert "prefix/obj" not in fake_gcs.objects
    _run(plugin.close())


def test_empty_object(fake_gcs):
    plugin = _plugin(fake_gcs)
    _run(plugin.write(WriteIO(path="empty", buf=memoryview(b""))))
    assert fake_gcs.objects["prefix/empty"] == b""
    _run(plugin.close())


def test_short_range_forces_offset_resync(fake_gcs, monkeypatch):
    """A 308 persisting only part of a chunk: the client must accept the
    server's Range as authoritative and continue from there."""
    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 1000)
    fake_gcs.faults.append({"kind": "chunk", "action": "short", "keep": 300})
    plugin = _plugin(fake_gcs)
    payload = bytes([i % 251 for i in range(3500)])
    _run(plugin.write(WriteIO(path="obj", buf=memoryview(payload))))
    assert fake_gcs.objects["prefix/obj"] == payload
    _run(plugin.close())


def test_http500_resyncs_via_probe(fake_gcs, monkeypatch):
    """Transient 500 mid-upload: retry must run the ``bytes */total``
    status probe and resume from the server's persisted offset."""
    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 1000)
    fake_gcs.faults.append({"kind": "chunk", "action": "http500"})
    fake_gcs.faults.append({"kind": "chunk", "action": "http500"})
    plugin = _plugin(fake_gcs)
    payload = bytes([i % 241 for i in range(3500)])
    _run(plugin.write(WriteIO(path="obj", buf=memoryview(payload))))
    assert fake_gcs.objects["prefix/obj"] == payload
    probes = [
        r for r in fake_gcs.request_log if r[0] == "PUT" and r[2] and r[2].startswith("bytes */")
    ]
    assert probes, "500 recovery must consult the status probe"
    _run(plugin.close())


def test_no_progress_308_retries_then_succeeds(fake_gcs, monkeypatch):
    """A 308 with no Range header (nothing persisted) must count as a
    failed attempt — backoff, resync, then proceed."""
    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 1000)
    fake_gcs.faults.append({"kind": "chunk", "action": "no_progress"})
    plugin = _plugin(fake_gcs)
    payload = bytes([i % 199 for i in range(2200)])
    _run(plugin.write(WriteIO(path="obj", buf=memoryview(payload))))
    assert fake_gcs.objects["prefix/obj"] == payload
    _run(plugin.close())


def test_collective_deadline_expiry_aborts(fake_gcs, monkeypatch):
    """A permanently wedged backend must abort once the collective
    deadline expires instead of retrying forever."""
    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 1000)
    for _ in range(1000):
        fake_gcs.faults.append({"kind": "chunk", "action": "http500"})
    plugin = _plugin(fake_gcs, deadline_sec=1.5)
    payload = bytes(2000)
    with pytest.raises(Exception) as exc_info:
        _run(plugin.write(WriteIO(path="obj", buf=memoryview(payload))))
    assert "prefix/obj" not in fake_gcs.objects
    _run(plugin.close())


def test_chunked_ranged_download_reassembly(fake_gcs, monkeypatch):
    """Downloads larger than the chunk size are reassembled from multiple
    ranged GETs; explicit byte_range reads slice correctly."""
    monkeypatch.setattr(gcs_mod, "_DOWNLOAD_CHUNK_SIZE", 700)
    plugin = _plugin(fake_gcs)
    payload = bytes([i % 233 for i in range(5000)])
    fake_gcs.objects["prefix/obj"] = payload
    read_io = ReadIO(path="obj")
    _run(plugin.read(read_io))
    assert read_io.buf.getvalue() == payload
    media_gets = [r for r in fake_gcs.request_log if r[0] == "GET" and "alt=media" in r[1]]
    assert len(media_gets) >= 8  # 5000 / 700 -> 8 ranged chunks
    ranged = ReadIO(path="obj", byte_range=(123, 2600))
    _run(plugin.read(ranged))
    assert ranged.buf.getvalue() == payload[123:2600]
    _run(plugin.close())


def test_transient_download_500_retried(fake_gcs, monkeypatch):
    monkeypatch.setattr(gcs_mod, "_DOWNLOAD_CHUNK_SIZE", 700)
    fake_gcs.faults.append({"kind": "download", "action": "http500"})
    plugin = _plugin(fake_gcs)
    payload = bytes([i % 229 for i in range(2000)])
    fake_gcs.objects["prefix/obj"] = payload
    read_io = ReadIO(path="obj")
    _run(plugin.read(read_io))
    assert read_io.buf.getvalue() == payload
    _run(plugin.close())


def test_snapshot_end_to_end_against_fake_gcs(fake_gcs, monkeypatch):
    """Full Snapshot.take/restore through the gs:// scheme with faults."""
    import numpy as np

    from tpusnap import Snapshot, StateDict

    monkeypatch.setattr(gcs_mod, "_UPLOAD_CHUNK_SIZE", 4096)
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", fake_gcs.endpoint)
    fake_gcs.faults.append({"kind": "chunk", "action": "http500"})
    fake_gcs.faults.append({"kind": "chunk", "action": "short", "keep": 1000})
    state = StateDict(
        w=np.arange(8192, dtype=np.float32), step=7, name="run1"
    )
    app_state = {"s": state}
    Snapshot.take("gs://bkt/snaps/s0", app_state)
    target = StateDict(
        w=np.zeros(8192, dtype=np.float32), step=0, name=""
    )
    app2 = {"s": target}
    Snapshot("gs://bkt/snaps/s0").restore(app2)
    assert np.array_equal(target["w"], state["w"])
    assert target["step"] == 7 and target["name"] == "run1"


def test_in_place_read_with_fused_crc(fake_gcs, monkeypatch):
    """ReadIO.into lands chunked downloads directly in the destination
    with the checksum accumulated chunk by chunk (the 7B-from-GCS
    restore path)."""
    import numpy as np

    from tpusnap import _native

    monkeypatch.setattr(gcs_mod, "_DOWNLOAD_CHUNK_SIZE", 1024)
    plugin = _plugin(fake_gcs)
    payload = bytes(range(256)) * 17  # 4352 bytes -> 5 download chunks
    _run(plugin.write(WriteIO(path="obj", buf=memoryview(payload))))

    dst = np.zeros(len(payload), dtype=np.uint8)
    read_io = ReadIO(path="obj", into=memoryview(dst), want_crc=True)
    _run(plugin.read(read_io))
    assert read_io.in_place
    assert dst.tobytes() == payload
    assert read_io.crc32c == _native.crc32c(payload)
    assert read_io.crc_algo == _native.checksum_algorithm()
    # generic buf view still works
    assert bytes(read_io.buf.getbuffer()) == payload

    # byte-ranged in-place read
    dst2 = np.zeros(2000, dtype=np.uint8)
    read_io = ReadIO(
        path="obj", byte_range=(100, 2100), into=memoryview(dst2), want_crc=True
    )
    _run(plugin.read(read_io))
    assert dst2.tobytes() == payload[100:2100]
    assert read_io.crc32c == _native.crc32c(payload[100:2100])
    _run(plugin.close())


def test_in_place_restore_end_to_end_gcs(fake_gcs, monkeypatch):
    """Snapshot restore through gs:// uses in-place reads for numpy
    targets; corruption in the bucket is detected."""
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap._native import ChecksumError

    monkeypatch.setenv("STORAGE_EMULATOR_HOST", fake_gcs.endpoint)
    arr = np.random.default_rng(0).standard_normal(50_000).astype(np.float32)
    Snapshot.take("gs://bkt/snaps/ip", {"s": StateDict(w=arr.copy())})
    target_arr = np.zeros_like(arr)
    Snapshot("gs://bkt/snaps/ip").restore({"s": StateDict(w=target_arr)})
    assert np.array_equal(target_arr, arr)

    # flip one byte of the stored blob in the bucket
    for name, blob in list(fake_gcs.objects.items()):
        if name.endswith("s/w") or "batched" in name:
            mutated = bytearray(blob)
            mutated[64] ^= 0xFF
            fake_gcs.objects[name] = bytes(mutated)
            break
    else:
        raise AssertionError(f"blob not found in {list(fake_gcs.objects)}")
    with pytest.raises(ChecksumError, match="w"):
        Snapshot("gs://bkt/snaps/ip").restore(
            {"s": StateDict(w=np.zeros_like(arr))}
        )


def test_scrub_verifies_and_detects_through_gcs(fake_gcs, monkeypatch):
    """verify_snapshot through gs:// exercises the non-in-place verify
    branch (the plugin fills ReadIO.buf; no fused read CRC), and must
    detect server-side bit rot."""
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot

    monkeypatch.setenv("STORAGE_EMULATOR_HOST", fake_gcs.endpoint)
    state = StateDict(w=np.arange(8192, dtype=np.float32), step=7)
    Snapshot.take("gs://bkt/snaps/scrub", {"s": state})
    opts = {"api_endpoint": fake_gcs.endpoint, "deadline_sec": 30.0}
    report = verify_snapshot("gs://bkt/snaps/scrub", storage_options=opts)
    assert report.clean and report.ok > 0

    # Flip a byte inside a stored blob on the "server".
    blob_names = [
        k for k in fake_gcs.objects if not k.endswith(".snapshot_metadata")
    ]
    assert blob_names
    name = max(blob_names, key=lambda k: len(fake_gcs.objects[k]))
    data = bytearray(fake_gcs.objects[name])
    data[10] ^= 0xFF
    fake_gcs.objects[name] = bytes(data)
    report = verify_snapshot("gs://bkt/snaps/scrub", storage_options=opts)
    assert not report.clean
    assert report.corrupt >= 1


def test_incremental_snapshot_through_gcs(fake_gcs, monkeypatch):
    """Cross-snapshot '../base/...' references resolve through the gs://
    key namespace (client-side normpath in _object_name)."""
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.knobs import override_batching_disabled

    monkeypatch.setenv("STORAGE_EMULATOR_HOST", fake_gcs.endpoint)
    opts = {"api_endpoint": fake_gcs.endpoint, "deadline_sec": 30.0}
    state = StateDict(w=np.arange(8192, dtype=np.float32), step=1)
    with override_batching_disabled(True):
        Snapshot.take("gs://bkt/snaps/s0", {"s": state})
        n_before = len(fake_gcs.objects)
        Snapshot.take(
            "gs://bkt/snaps/s1",
            {"s": state},
            incremental_from="gs://bkt/snaps/s0",
        )
    # Only s1's metadata (plus the telemetry sidecar) was uploaded; w
    # deduped against s0's blob — no payload bytes moved.
    new = {
        k
        for k in fake_gcs.objects
        if "snaps/s1" in k and ".tpusnap/" not in k
    }
    assert new == {"snaps/s1/.snapshot_metadata"}, new
    n_sidecars = sum(
        1 for k in fake_gcs.objects if "snaps/s1" in k and ".tpusnap/" in k
    )
    assert len(fake_gcs.objects) == n_before + 1 + n_sidecars
    target = StateDict(w=np.zeros(8192, dtype=np.float32), step=0)
    Snapshot("gs://bkt/snaps/s1", storage_options=opts).restore({"s": target})
    assert np.array_equal(target["w"], state["w"]) and target["step"] == 1
    assert verify_snapshot("gs://bkt/snaps/s1", storage_options=opts).clean


def test_materialize_through_gcs(fake_gcs, monkeypatch):
    """materialize copies base blobs within the gs:// namespace and
    rewrites the manifest; the base can then be deleted server-side."""
    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.inspect import materialize_snapshot
    from tpusnap.knobs import override_batching_disabled

    monkeypatch.setenv("STORAGE_EMULATOR_HOST", fake_gcs.endpoint)
    opts = {"api_endpoint": fake_gcs.endpoint, "deadline_sec": 30.0}
    state = StateDict(w=np.arange(8192, dtype=np.float32), step=1)
    with override_batching_disabled(True):
        Snapshot.take("gs://bkt/snaps/m0", {"s": state})
        Snapshot.take(
            "gs://bkt/snaps/m1",
            {"s": state},
            incremental_from="gs://bkt/snaps/m0",
        )
    stats = materialize_snapshot("gs://bkt/snaps/m1", storage_options=opts)
    assert stats["blobs_copied"] == 1
    # Delete the base server-side; the materialized snapshot stands alone.
    for k in list(fake_gcs.objects):
        if "snaps/m0" in k:
            del fake_gcs.objects[k]
    assert verify_snapshot("gs://bkt/snaps/m1", storage_options=opts).clean
    target = StateDict(w=np.zeros(8192, dtype=np.float32), step=0)
    Snapshot("gs://bkt/snaps/m1", storage_options=opts).restore({"s": target})
    assert np.array_equal(target["w"], state["w"]) and target["step"] == 1


def test_gcs_pipeline_benchmark_smoke():
    """The benchmarks/gcs_pipeline harness (cloud-path throughput via
    the fake server with injected latency) runs end to end, verifies
    its restore, and reports pipeline concurrency."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "benchmarks", "gcs_pipeline", "main.py"),
            "--total-mb", "16",
            "--latency-ms", "5",
            "--upload-chunk-mb", "1",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "restore verified: True" in proc.stdout
    assert "concurrency" in proc.stdout
