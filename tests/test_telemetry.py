"""Telemetry subsystem tests: span/counter recording, knob gating,
persisted Chrome traces, the cross-rank rollup, the ``trace`` CLI,
chaos-layer integration (injected faults + retries visible in the
trace), the RSS sampler, and the tier-1 overhead guard.
"""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

from tpusnap import (
    FaultPlan,
    MetricsSink,
    PytreeState,
    Snapshot,
    metrics_sink,
)
from tpusnap import telemetry
from tpusnap.knobs import is_telemetry_enabled, override_telemetry_enabled
from tpusnap.telemetry import (
    TakeTelemetry,
    rollup_summaries,
    telemetry_rank_path,
)


def _state(total_bytes=1 << 20, n=2):
    per = max(total_bytes // n // 4, 16)
    return {f"w{i}": np.arange(per, dtype=np.float32) + i for i in range(n)}


def _trace_file(snap_path, rank=0):
    return os.path.join(snap_path, ".tpusnap", "telemetry", f"rank_{rank}.json")


# ------------------------------------------------------------------ knob


def test_telemetry_knob_default_on():
    assert is_telemetry_enabled()


def test_telemetry_knob_env_and_override(monkeypatch):
    monkeypatch.setenv("TPUSNAP_TELEMETRY", "0")
    assert not is_telemetry_enabled()
    monkeypatch.setenv("TPUSNAP_TELEMETRY", "1")
    assert is_telemetry_enabled()
    with override_telemetry_enabled(False):
        assert not is_telemetry_enabled()
        with override_telemetry_enabled(True):
            assert is_telemetry_enabled()
        assert not is_telemetry_enabled()
    assert is_telemetry_enabled()


# ------------------------------------------------------- unit: recorder


def test_span_recording_and_summary_aggregates():
    rec = TakeTelemetry(rank=3, enabled=True)
    rec.record_span("x", 0.0, 0.2)
    rec.record_span("x", 0.2, 0.4)
    rec.record_span("x", 0.6, 0.6)
    rec.record_span("p", 0.0, 1.0, phase=True)
    rec.incr("c", 2)
    rec.incr("c")
    rec.gauge_max("g", 5.0)
    rec.gauge_max("g", 3.0)
    rec.finalize()
    s = rec.summary()
    assert s["rank"] == 3
    assert s["stages"]["x"]["count"] == 3
    assert s["stages"]["x"]["max_s"] == pytest.approx(0.6)
    assert s["stages"]["x"]["p50_s"] == pytest.approx(0.4)
    assert s["stages"]["x"]["total_s"] == pytest.approx(1.2)
    assert s["counters"]["c"] == 3
    assert s["gauges"]["g"] == 5.0
    assert s["phases"] == {"p": 1.0}


def test_spans_disabled_counters_still_on():
    rec = TakeTelemetry(rank=0, enabled=False)
    with rec.span("never"):
        pass
    rec.record_span("never", 0.0, 1.0)
    rec.event("never")
    rec.incr("still_counted")
    rec.finalize()
    s = rec.summary()
    assert s["stages"] == {}
    assert s["counters"] == {"still_counted": 1}
    assert not s["enabled"]


def test_counters_atomic_across_threads():
    rec = TakeTelemetry(rank=0, enabled=True)
    n_threads, n_incr = 8, 500

    def bump():
        for _ in range(n_incr):
            rec.incr("hits")

    threads = [threading.Thread(target=bump) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rec.finalize()
    assert rec.summary()["counters"]["hits"] == n_threads * n_incr


def test_module_incr_updates_global_and_current():
    telemetry.reset_global_counters()
    rec = telemetry.begin_take(rank=0)
    try:
        telemetry.incr("test.counter", 2)
        assert telemetry.counter_value("test.counter") == 2
        assert rec.summary()["counters"]["test.counter"] == 2
    finally:
        telemetry.end_take(rec)
    # No take in flight: global still counts (always-on).
    telemetry.incr("test.counter")
    assert telemetry.counter_value("test.counter") == 3


def test_chrome_trace_events_shape():
    rec = TakeTelemetry(rank=1, enabled=True)
    with rec.span("work", phase=True, bytes=10):
        pass
    rec.event("boom", kind="write")
    rec.finalize()
    events = rec.chrome_trace_events()
    complete = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    assert len(complete) == 1 and len(instants) == 1
    ev = complete[0]
    assert ev["name"] == "work" and ev["pid"] == 1
    assert isinstance(ev["ts"], float) and isinstance(ev["dur"], float)
    assert ev["args"] == {"bytes": 10}
    # Serializes as valid JSON end to end.
    doc = json.loads(rec.to_json())
    assert isinstance(doc["traceEvents"], list)


def test_rollup_summaries():
    a = {
        "take_wall_s": 1.0,
        "phase_coverage": 0.95,
        "stages": {"stage": {"count": 1, "total_s": 0.6, "p50_s": 0.6, "max_s": 0.6}},
        "counters": {"retry.attempts": 2, "storage.bytes_written": 100},
        "gauges": {"scheduler.budget_used_bytes": 50.0},
    }
    b = {
        "take_wall_s": 2.0,
        "phase_coverage": 0.91,
        "stages": {"stage": {"count": 1, "total_s": 0.8, "p50_s": 0.8, "max_s": 0.8}},
        "counters": {"retry.attempts": 1, "storage.bytes_written": 200},
        "gauges": {"scheduler.budget_used_bytes": 80.0},
    }
    r = rollup_summaries([a, b])
    assert r["ranks"] == 2
    assert r["take_wall_s"] == 2.0
    assert r["phase_coverage_min"] == 0.91
    assert r["stages"]["stage"]["max_s"] == pytest.approx(0.8)
    assert r["counters"]["retry.attempts"] == 3
    assert r["retry_attempts"] == 3
    assert r["bytes_written"] == 300
    assert r["budget_high_water_bytes"] == 80.0
    assert rollup_summaries([]) == {}


def test_metrics_sink_callbacks(tmp_path):
    seen = {"spans": [], "counters": [], "summaries": []}

    class Sink(MetricsSink):
        def on_span(self, name, duration_s, attrs):
            seen["spans"].append(name)

        def on_counter(self, name, delta, value):
            seen["counters"].append(name)

        def on_take_summary(self, summary):
            seen["summaries"].append(summary)

    with metrics_sink(Sink()):
        Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    assert "stage" in seen["spans"]
    assert "storage.writes" in seen["counters"]
    assert len(seen["summaries"]) == 1
    assert seen["summaries"][0]["phase_coverage"] > 0.5
    # Unregistered: no further callbacks.
    n = len(seen["counters"])
    telemetry.incr("post.unregister")
    assert len(seen["counters"]) == n


def test_raising_sink_never_breaks_a_take(tmp_path):
    class BadSink(MetricsSink):
        def on_span(self, name, duration_s, attrs):
            raise RuntimeError("bad sink")

        def on_counter(self, name, delta, value):
            raise RuntimeError("bad sink")

        def on_take_summary(self, summary):
            raise RuntimeError("bad sink")

    with metrics_sink(BadSink()):
        snap = Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    assert snap.verify().clean


def test_raising_sink_warns_once_per_callback_per_take(tmp_path, caplog):
    """A broken exporter must be diagnosable, not invisible: one
    rate-limited WARNING per sink class per callback per take, naming
    both — and the budget re-arms on the next take."""

    class BoomSink(MetricsSink):
        def on_span(self, name, duration_s, attrs):
            raise RuntimeError("boom")

        def on_counter(self, name, delta, value):
            raise RuntimeError("boom")

    def warnings_for(records, method):
        return [
            r
            for r in records
            if r.levelname == "WARNING"
            and "BoomSink" in r.message
            and method in r.message
        ]

    with metrics_sink(BoomSink()):
        with caplog.at_level(logging.WARNING, logger="tpusnap.telemetry"):
            Snapshot.take(str(tmp_path / "s1"), {"m": PytreeState(_state())})
        # Many spans and counters fired; exactly ONE warning per callback.
        assert len(warnings_for(caplog.records, "on_span")) == 1
        assert len(warnings_for(caplog.records, "on_counter")) == 1
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="tpusnap.telemetry"):
            Snapshot.take(str(tmp_path / "s2"), {"m": PytreeState(_state())})
        # Fresh take -> the one-warning budget re-arms.
        assert len(warnings_for(caplog.records, "on_span")) == 1


def test_metrics_sink_context_manager_unregisters_on_raise():
    """A failing test body can no longer leak its sink into the
    process-global tuple (the leak the context manager exists to fix)."""
    calls = []

    class Sink(MetricsSink):
        def on_counter(self, name, delta, value):
            calls.append(name)

    sink = Sink()
    with pytest.raises(RuntimeError):
        with metrics_sink(sink) as registered:
            assert registered is sink
            telemetry.incr("ctx.mgr.counter")
            raise RuntimeError("body failed")
    n = len(calls)
    assert n >= 1
    telemetry.incr("ctx.mgr.counter")  # after exit: no callback
    assert len(calls) == n


# ------------------------------------------------- persisted trace files


def test_take_persists_trace_and_rollup(tmp_path):
    path = str(tmp_path / "snap")
    snap = Snapshot.take(path, {"m": PytreeState(_state())})
    tf = _trace_file(path)
    assert os.path.exists(tf)
    doc = json.load(open(tf))
    assert doc["rank"] == 0
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert "ph" in ev and "pid" in ev
        if ev["ph"] in ("X", "i"):
            assert "ts" in ev and "name" in ev
    s = doc["summary"]
    # Acceptance: per-stage phases cover >= 90% of the take wall-clock.
    assert s["phase_coverage"] >= 0.9
    for phase in ("state_dict", "prepare", "stage", "io_drain"):
        assert phase in s["phases"], phase
    assert s["counters"]["storage.bytes_written"] > 0
    assert "peak_rss_delta_bytes" in s["gauges"]
    assert "scheduler.budget_used_bytes" in s["gauges"]
    # Rank-0 rollup rides the committed metadata extras.
    rollup = snap.metadata.extras["telemetry"]
    assert rollup["ranks"] == 1
    assert rollup["bytes_written"] == s["counters"]["storage.bytes_written"]
    # The trace sidecar files do not perturb integrity machinery.
    assert snap.verify().clean


def test_async_take_persists_trace(tmp_path):
    path = str(tmp_path / "snap")
    pending = Snapshot.async_take(path, {"m": PytreeState(_state())})
    snap = pending.wait()
    doc = json.load(open(_trace_file(path)))
    assert doc["summary"]["phase_coverage"] >= 0.85
    assert "io_drain" in doc["summary"]["phases"]
    assert "telemetry" in snap.metadata.extras


def test_telemetry_disabled_skips_trace_file(tmp_path):
    path = str(tmp_path / "snap")
    with override_telemetry_enabled(False):
        snap = Snapshot.take(path, {"m": PytreeState(_state())})
    assert not os.path.exists(_trace_file(path))
    # Counters are always-on: the rollup still lands in the extras.
    rollup = (snap.metadata.extras or {}).get("telemetry")
    assert rollup is not None
    assert rollup["bytes_written"] > 0
    assert rollup["stages"] == {}


def test_last_take_summary_exposed(tmp_path):
    Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    s = telemetry.LAST_TAKE_SUMMARY
    assert s is not None and s["counters"]["storage.writes"] >= 1


def test_clean_take_records_no_fatal_payload_retries(tmp_path):
    """Regression (BENCH_r06 stray ``retry.fatal.read: 1``): the journal
    probe at take start 404s on every fresh path, and other
    sidecar-namespace misses are expected probes, not payload failures —
    none of them may surface as ``retry.fatal.*`` payload counters in
    the take's stage_breakdown. The sidecar family keeps its own label
    (``retry.fatal.sidecar.*``) so real sidecar storage failures stay
    observable."""
    Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    counters = telemetry.LAST_TAKE_SUMMARY["counters"]
    fatal_payload = {
        k: v
        for k, v in counters.items()
        if k.startswith("retry.fatal.")
        and not k.startswith("retry.fatal.sidecar.")
    }
    assert not fatal_payload, fatal_payload
    # The probe that used to pollute the payload counter is the journal
    # read; on a fresh path it lands under the sidecar family instead.
    assert counters.get("retry.fatal.sidecar.read", 0) >= 1, counters


# ------------------------------------------------------------ trace CLI


def test_trace_cli_renders_and_json(tmp_path, capsys):
    from tpusnap.__main__ import main

    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": PytreeState(_state())})
    assert main(["trace", path]) == 0
    out = capsys.readouterr().out
    assert "stage" in out and "phase coverage" in out
    assert main(["trace", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rollup"]["ranks"] == 1
    assert "0" in doc["ranks"]
    assert main(["trace", path, "--rank", "0"]) == 0
    assert "rank 0 stages" in capsys.readouterr().out


def test_trace_cli_no_telemetry_exits_3(tmp_path, capsys):
    from tpusnap.__main__ import main

    path = str(tmp_path / "snap")
    with override_telemetry_enabled(False):
        snap = Snapshot.take(path, {"m": PytreeState(_state())})
    # Strip the always-on rollup too: simulate a pre-telemetry snapshot.
    meta = json.load(open(os.path.join(path, ".snapshot_metadata")))
    meta.pop("extras", None)
    # Rewriting the file invalidates its self-checksum; per the format
    # spec a rewriter strips (or recomputes) the field.
    meta.pop("self_checksum", None)
    with open(os.path.join(path, ".snapshot_metadata"), "w") as f:
        json.dump(meta, f)
    del snap
    assert main(["trace", path]) == 3
    assert "no telemetry" in capsys.readouterr().err


def test_trace_cli_knob_off_take_exits_3(tmp_path, capsys):
    """The OTHER no-telemetry case: a knob-off take still rolls up its
    always-on counters into the extras, but has zero spans anywhere —
    trace must print the one-line explanation and exit 3 instead of an
    empty stage table."""
    from tpusnap.__main__ import main

    path = str(tmp_path / "snap")
    with override_telemetry_enabled(False):
        Snapshot.take(path, {"m": PytreeState(_state())})
    assert main(["trace", path]) == 3
    captured = capsys.readouterr()
    assert "no telemetry" in captured.err
    assert "stage" not in captured.out  # no empty table printed


def test_cli_help_lists_trace(capsys):
    from tpusnap.__main__ import main

    assert main(["--help"]) == 0
    assert "trace" in capsys.readouterr().out


# ----------------------------------------------------- chaos integration


@pytest.mark.chaos
def test_chaos_trace_records_faults_and_retries(tmp_path, caplog):
    path = str(tmp_path / "chaos_snap")
    with caplog.at_level(logging.INFO, logger="tpusnap.retry"):
        Snapshot.take(
            "chaos+fs://" + path,
            {"m": PytreeState(_state())},
            storage_options={"fault_plan": FaultPlan(seed=3, transient_per_op=1)},
        )
    doc = json.load(open(_trace_file(path)))
    counters = doc["summary"]["counters"]
    assert counters.get("faults.injected.write", 0) >= 1
    assert counters.get("retry.attempts", 0) >= 1
    assert counters.get("retry.recovered", 0) >= 1
    assert any(
        k.startswith("retry.transient.write.InjectedFaultError")
        for k in counters
    )
    # The injected faults + retries appear as instant events in the trace.
    instants = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "i"}
    assert "fault_injected" in instants and "retry" in instants
    # Success-after-retry now logs the attempt count at INFO.
    assert any("succeeded after" in r.message for r in caplog.records)
    # And the committed rollup carries the fault/retry counters.
    md = json.load(open(os.path.join(path, ".snapshot_metadata")))
    assert md["extras"]["telemetry"]["retry_attempts"] >= 1


# ---------------------------------------------------------- RSS sampler


def test_rss_sampler_start_stop_clean():
    from tpusnap.rss_profiler import RSSSampler

    sampler = RSSSampler(interval_sec=0.02)
    sampler.start()
    time.sleep(0.08)
    deltas = sampler.stop()
    assert deltas, "sampler recorded nothing"
    assert all(isinstance(d, int) for d in deltas)
    # Idempotent stop, thread actually gone.
    n = len(deltas)
    assert sampler.stop() is deltas and len(deltas) == n
    assert not any(t.name == "tpusnap-rss" for t in threading.enumerate())


def test_rss_sampler_records_final_delta_for_sub_interval_context():
    from tpusnap.rss_profiler import RSSSampler

    sampler = RSSSampler(interval_sec=10.0)
    sampler.start()
    deltas = sampler.stop()  # stop long before the first interval tick
    assert len(deltas) == 1  # the final sample


def test_measure_rss_deltas_context_manager():
    from tpusnap.rss_profiler import measure_rss_deltas

    deltas = []
    with measure_rss_deltas(deltas, interval_sec=0.01):
        blob = np.ones(4 << 20, dtype=np.uint8)  # ~4MB so RSS moves
        time.sleep(0.05)
        del blob
    assert deltas
    assert deltas[-1] is not None  # final delta appended on exit


def test_take_summary_includes_peak_rss(tmp_path):
    Snapshot.take(str(tmp_path / "snap"), {"m": PytreeState(_state())})
    assert "peak_rss_delta_bytes" in telemetry.LAST_TAKE_SUMMARY["gauges"]


# -------------------------------------------------------- overhead guard


def test_telemetry_overhead_within_bound(tmp_path):
    """Tier-1 guard: a small take with telemetry enabled stays within
    10% (+50ms absolute timing slack) of disabled — catches accidental
    hot-path regressions (per-element spans, lock convoys) without
    flaking on scheduler noise. min-of-N so one slow run cannot fail it."""
    state = _state(total_bytes=16 << 20, n=8)

    def take_once(i, enabled):
        with override_telemetry_enabled(enabled):
            t0 = time.perf_counter()
            Snapshot.take(
                str(tmp_path / f"s_{enabled}_{i}"), {"m": PytreeState(state)}
            )
            return time.perf_counter() - t0

    take_once(99, True)  # warmup: imports, native lib load
    runs = 5
    disabled = min(take_once(i, False) for i in range(runs))
    enabled = min(take_once(i, True) for i in range(runs))
    assert enabled <= disabled * 1.10 + 0.05, (
        f"telemetry overhead too high: enabled {enabled:.3f}s vs "
        f"disabled {disabled:.3f}s"
    )


# ------------------------------------------------------------ distributed


def _world_telemetry_take(snap_dir):
    import jax.numpy as jnp

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    state = StateDict(
        w=jnp.arange(4096, dtype=jnp.float32) * (comm.rank + 1),
        b=jnp.ones(64, jnp.float32),
    )
    Snapshot.take(snap_dir, {"model": state})
    comm.barrier()
    if comm.rank == 0:
        for r in range(comm.world_size):
            p = os.path.join(snap_dir, ".tpusnap", "telemetry", f"rank_{r}.json")
            assert os.path.exists(p), f"missing trace for rank {r}"
            doc = json.load(open(p))
            assert doc["traceEvents"], f"rank {r} trace empty"
            assert doc["summary"]["phase_coverage"] >= 0.9, doc["summary"]
        md = json.load(open(os.path.join(snap_dir, ".snapshot_metadata")))
        rollup = md["extras"]["telemetry"]
        assert rollup["ranks"] == comm.world_size
        # Collective waits are visible per rank.
        assert "comm.all_gather" in rollup["stages"]
        assert rollup["bytes_written"] > 0


@pytest.mark.distributed
def test_distributed_take_produces_rank_traces(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    run_subprocess_world(
        _world_telemetry_take, world_size=2, args=[str(tmp_path / "snap")]
    )
