"""Checkpoint-SLO subsystem tests (tpusnap/slo.py + its seams).

Covers: SLOTracker math on fake clocks (RPO, commit interval,
data-at-risk evidence tiers), the history-derived RTO estimator
(sufficient / insufficient / phase-aware), the sidecar + `slo` CLI
exit contract (0 healthy / 2 breach / 3 insufficient), Prometheus
exposition of the four gauge families through
``parse_prometheus_textfile`` (the acceptance self-check), the fleet
fold, the heartbeat/`watch` exposure columns, the history event's
``slo`` section — and the crash-matrix acceptance: a SIGKILLed take
whose pre-kill exported ``tpusnap_data_at_risk_bytes`` must match the
bytes the salvage/retake actually re-did, with the measured restore
within the documented ≤2x factor of the pre-crash
``tpusnap_estimated_rto_seconds``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict
from tpusnap import slo as slo_mod
from tpusnap.knobs import (
    override_heartbeat_interval_s,
    override_metrics_dir,
    override_metrics_export,
    override_slo_thresholds,
    override_telemetry_dir,
)
from tpusnap.metrics_export import (
    PrometheusTextfileSink,
    install_env_sinks,
    parse_prometheus_textfile,
)
from tpusnap.slo import (
    RTOEstimate,
    SLOTracker,
    estimate_rto,
    evaluate_records,
    read_slo_records,
    slo_rank_path,
)


@pytest.fixture
def slo_env(tmp_path):
    """Isolated telemetry/metrics dirs + a fresh process-global tracker
    (the tracker is process-global state like the telemetry counters)."""
    slo_mod.reset_tracker()
    with override_telemetry_dir(str(tmp_path / "tele")), override_metrics_dir(
        str(tmp_path / "tele")
    ):
        yield str(tmp_path / "tele")
    slo_mod.reset_tracker()
    install_env_sinks()


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _tracker(clock=None, wall=None):
    clock = clock or FakeClock()
    wall = wall or FakeClock(1_700_000_000.0)
    return SLOTracker(clock=clock, wall=wall), clock, wall


# ------------------------------------------------------------ tracker math


def test_rpo_counts_from_tracker_start_before_any_commit(slo_env):
    t, clock, _ = _tracker()
    clock.advance(12.5)
    assert t.rpo_s() == pytest.approx(12.5)


def test_commit_anchors_rpo_and_interval(slo_env):
    t, clock, _ = _tracker()
    clock.advance(10.0)
    sec = t.record_commit("t1", "/p", snapshot_bytes=1000)
    assert sec["commit_interval_s"] == pytest.approx(10.0)
    clock.advance(4.0)
    assert t.rpo_s() == pytest.approx(4.0)
    sec2 = t.record_commit("t2", "/p", snapshot_bytes=1000)
    assert sec2["commit_interval_s"] == pytest.approx(4.0)
    assert t.rpo_s() == pytest.approx(0.0)


def test_data_at_risk_evidence_tiers(slo_env):
    t, _clock, _ = _tracker()
    # Tier 1: explicit steps accumulate.
    t.record_step(100)
    t.record_step(50)
    assert t.data_at_risk_bytes() == 150
    # Tier 3: planned payload floors the figure (conservative max).
    t.note_planned(1000, incremental=False)
    assert t.data_at_risk_bytes() == 1000
    t.record_step(2000)
    assert t.data_at_risk_bytes() == 2150
    # Commit clears the planned payload and the PRE-capture steps; the
    # 2000 recorded after the capture is not in the snapshot and stays
    # at risk. The interval's realized change bounds the explicit tier
    # at its capture-time value (150) — post-capture bytes belong to
    # the NEXT interval's event, never double-counted.
    sec = t.record_commit("t1", "/p", snapshot_bytes=1000)
    assert sec["change_bytes"] == 1000
    assert t.data_at_risk_bytes() == 2000
    assert t.rpo_s() == pytest.approx(0.0)


def test_commit_anchors_at_capture_not_commit(slo_env):
    """An async take's drain can run minutes after staging: the commit
    makes the CAPTURE instant durable, so the RPO clock restarts from
    capture time and drain-window step evidence survives the commit."""
    t, clock, _ = _tracker()
    t.record_step(100)  # pre-capture: durable once the take commits
    clock.advance(10.0)
    t.note_planned(1000, incremental=False, take_id="t1")  # capture @110
    clock.advance(60.0)  # the drain window
    t.record_step(500)  # post-capture: NOT in the snapshot
    sec = t.record_commit("t1", "/p", snapshot_bytes=1000)
    # RPO measured from capture, not commit.
    assert t.rpo_s() == pytest.approx(60.0)
    assert sec["commit_interval_s"] == pytest.approx(10.0)
    # The interval's change excludes the drain-window 500 (it will be
    # the NEXT interval's change, not this one's — no double count).
    assert sec["change_bytes"] == 1000
    # Drain-window mutation stays at risk; pre-capture step cleared.
    assert t.data_at_risk_bytes() == 500


def test_incremental_change_stats_subtract_dedup_skips(slo_env):
    t, _clock, _ = _tracker()
    counters = {"scheduler.dedup_skipped_bytes": 0}
    t.note_planned(1000, incremental=True, live_counters=lambda: counters)
    assert t.data_at_risk_bytes() == 1000
    # The dual-hash pass proves 800 bytes unchanged: exposure shrinks live.
    counters["scheduler.dedup_skipped_bytes"] = 800
    assert t.data_at_risk_bytes() == 200
    sec = t.record_commit(
        "t1", "/p", snapshot_bytes=1000, incremental=True, counters=counters
    )
    assert sec["change_bytes"] == 200


def test_abort_releases_recorder_but_keeps_exposure(slo_env):
    """An aborted take must release the dead take's counter closure
    (memory) without clearing the at-risk figure — nothing committed,
    the planned bytes are still exposure. Incremental refinement is
    frozen at the last observed skip evidence."""
    t, _clock, _ = _tracker()
    counters = {"scheduler.dedup_skipped_bytes": 300}
    t.note_planned(1000, incremental=True, live_counters=lambda: counters)
    assert t.data_at_risk_bytes() == 700
    t.note_take_aborted()
    assert t._live_counters is None
    counters["scheduler.dedup_skipped_bytes"] = 999  # dead take: ignored
    assert t.data_at_risk_bytes() == 700


def test_failed_take_keeps_data_at_risk(slo_env, tmp_path):
    """End-to-end abort path: a take that dies must leave the exposure
    standing — the explicit step evidence survives the abort — and the
    next successful commit clears it."""
    from tpusnap import FaultPlan, InjectedFaultError, record_slo_step

    state = {"a": StateDict(w=np.arange(50000, dtype=np.float32))}
    record_slo_step(200000)
    # Mark a live-counter closure as if a take were mid-flight, then
    # fail a real take: on_failure must release the closure while the
    # exposure stands.
    with pytest.raises(InjectedFaultError):
        Snapshot.take(
            "chaos+fs://" + str(tmp_path / "fail"),
            state,
            storage_options={
                "fault_plan": FaultPlan(transient_per_op=99),
                "retry": False,
            },
        )
    assert slo_mod.tracker().data_at_risk_bytes() == 200000
    assert slo_mod.tracker()._live_counters is None  # recorder released
    Snapshot.take(str(tmp_path / "ok"), state)
    assert slo_mod.tracker().data_at_risk_bytes() == 0


def test_exit_marker_clean_vs_crash(tmp_path):
    """Clean interpreter exit stamps the sidecar final (exposure
    frozen); an unhandled-exception crash — which ALSO runs atexit —
    must NOT be stamped, so the gate keeps screaming about it."""
    tele = str(tmp_path / "tele")
    env = dict(os.environ, JAX_PLATFORMS="cpu", TPUSNAP_TELEMETRY_DIR=tele)
    child = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import numpy as np, sys\n"
        "from tpusnap import Snapshot, StateDict\n"
        "Snapshot.take(sys.argv[1], {'a': StateDict(w=np.arange(1000))})\n"
        "if sys.argv[2] == 'crash':\n"
        "    raise RuntimeError('simulated training crash')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", child, str(tmp_path / "s1"), "clean"],
        env=env, timeout=180,
    )
    assert r.returncode == 0
    assert json.load(open(os.path.join(tele, "slo", "rank_0.json")))["final"]
    r = subprocess.run(
        [sys.executable, "-c", child, str(tmp_path / "s2"), "crash"],
        env=env, capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 1
    rec = json.load(open(os.path.join(tele, "slo", "rank_0.json")))
    assert not rec.get("final")


def test_telemetry_off_take_still_anchors(slo_env, tmp_path):
    """The SLO tracker is bookkeeping, not spans: with TPUSNAP_TELEMETRY=0
    (no pump, no attach) the commit must still anchor and publish the
    sidecar with the rank configured."""
    from tpusnap.knobs import override_telemetry_enabled

    with override_telemetry_enabled(False):
        Snapshot.take(
            str(tmp_path / "s"),
            {"a": StateDict(w=np.arange(50000, dtype=np.float32))},
        )
    recs = read_slo_records()
    assert len(recs) == 1
    assert recs[0]["last_commit_ts"] is not None
    assert recs[0]["world_size"] == 1


def test_breach_is_edge_triggered(slo_env):
    from tpusnap import telemetry

    telemetry.reset_global_counters()
    t, clock, _ = _tracker()
    with override_slo_thresholds(rpo_s=5.0):
        clock.advance(10.0)  # over threshold
        t.publish(force=True)
        t.publish(force=True)  # same episode: no second fire
        assert telemetry.counter_value("slo.breaches") == 1
        t.record_commit("t1", "/p", snapshot_bytes=10)  # re-arms
        clock.advance(10.0)
        t.publish(force=True)
        assert telemetry.counter_value("slo.breaches") == 2


# ----------------------------------------------------------- RTO estimator


def _restore_event(wall_s, nbytes, read_s=None, rank=0):
    ev = {"kind": "restore", "rank": rank, "wall_s": wall_s, "bytes": nbytes}
    if read_s is not None:
        ev["phases_s"] = {"restore.read": read_s}
    return ev


def test_estimate_rto_insufficient_history():
    est = estimate_rto(10**9, events=[_restore_event(1.0, 10**9)] * 2)
    assert not est.ok and est.n_baseline == 2
    assert "need 3" in est.reason


def test_estimate_rto_scales_bytes_and_adds_overhead():
    # 1 GB read in 1 s (+0.5 s overhead), three times over.
    events = [_restore_event(1.5, 10**9, read_s=1.0) for _ in range(3)]
    est = estimate_rto(4 * 10**9, events=events)
    assert est.ok and est.read_gbps == pytest.approx(1.0)
    assert est.seconds == pytest.approx(4.5, rel=1e-3)
    # Without phase data the whole wall prices the bytes (overhead 0).
    events = [_restore_event(2.0, 10**9) for _ in range(3)]
    est = estimate_rto(10**9, events=events)
    assert est.ok and est.seconds == pytest.approx(2.0, rel=1e-3)


def test_estimate_rto_ignores_other_kinds_and_ranks():
    events = (
        [{"kind": "take", "rank": 0, "wall_s": 9.0, "bytes": 10**9}] * 5
        + [_restore_event(1.0, 10**9, rank=1)] * 5
        + [_restore_event(1.0, 10**9)] * 3
    )
    est = estimate_rto(10**9, events=events)
    assert est.ok and est.n_baseline == 3


# ------------------------------------------------- records + gate verdicts


def _record(rank=0, last_commit_age=10.0, at_risk=0, rto=None, now=1000.0):
    return {
        "v": 1,
        "rank": rank,
        "world_size": 1,
        "ts": now - 1.0,
        "started_ts": now - 500.0,
        "last_commit_ts": now - last_commit_age,
        "data_at_risk_bytes": at_risk,
        "estimated_rto_s": rto,
    }


def test_evaluate_records_verdicts():
    now = 1000.0
    # Healthy under thresholds.
    rep = evaluate_records(
        [_record(last_commit_age=10, rto=5.0, now=now)],
        rpo_threshold_s=60,
        rto_threshold_s=60,
        now=now,
    )
    assert rep["verdict"] == "healthy"
    # Live recomputation from wall anchors: a stale record still breaches.
    rep = evaluate_records(
        [_record(last_commit_age=120, now=now)],
        rpo_threshold_s=60,
        now=now,
    )
    assert rep["verdict"] == "breach"
    assert rep["ranks"][0]["since_commit_s"] == pytest.approx(120.0)
    # RTO objective set but no estimate anywhere: no verdict.
    rep = evaluate_records(
        [_record(last_commit_age=10, rto=None, now=now)],
        rto_threshold_s=60,
        now=now,
    )
    assert rep["verdict"] == "insufficient"
    # No records at all.
    assert evaluate_records([], now=now)["verdict"] == "insufficient"
    # Never-committed record: exposure counts from tracker start.
    rec = _record(now=now)
    rec["last_commit_ts"] = None
    rep = evaluate_records([rec], rpo_threshold_s=60, now=now)
    assert rep["verdict"] == "breach"
    assert rep["ranks"][0]["since_commit_s"] == pytest.approx(500.0)


def test_final_record_freezes_exposure():
    """A record marked `final` (clean process exit) freezes
    since-commit at its write time — a finished run is not an incident;
    an unmarked (SIGKILLed/live) record keeps growing."""
    now = 10_000.0
    rec = _record(last_commit_age=30, now=1000.0)
    rec["ts"] = 1000.0 - 1.0
    rec["final"] = True
    rep = evaluate_records([rec], rpo_threshold_s=60, now=now)
    assert rep["verdict"] == "healthy"
    assert rep["ranks"][0]["since_commit_s"] == pytest.approx(29.0)
    del rec["final"]
    rep = evaluate_records([rec], rpo_threshold_s=60, now=now)
    assert rep["verdict"] == "breach"


def test_fleet_fold_adds_record_staleness():
    """A hung rank's frozen heartbeat must not freeze the fleet RPO:
    the fold adds how stale each record is."""

    class FakeKV:
        def try_get_dir(self, prefix):
            return {
                f"{prefix}1": json.dumps(
                    {"ts": 500.0, "slo": {"rpo_s": 40.0,
                                          "data_at_risk_bytes": 1}}
                ).encode(),
            }

    wall = FakeClock(800.0)  # record is 300s stale
    t = SLOTracker(clock=FakeClock(), wall=wall)
    t.configure(rank=0, world_size=2)
    t._fold_fleet("take1", FakeKV())
    assert t.snapshot_state()["fleet"]["rpo_s"] == pytest.approx(340.0)


def test_rto_estimator_uses_own_rank(slo_env):
    """A host running only ranks >= 8 must form its estimate from its
    own ranks' restore events, not wait for rank-0 events forever."""
    from tpusnap.history import record_event

    for _ in range(3):
        record_event(_restore_event(1.0, 10**9, read_s=1.0, rank=8))
    t, _clock, _ = _tracker()
    t.configure(rank=8, world_size=16)
    t.note_planned(10**9, incremental=False)
    assert t.snapshot_state()["estimated_rto_s"] is not None


def test_cli_exit_contract(slo_env, tmp_path):
    """slo --check: 0 healthy / 2 breach / 3 insufficient — unit leg of
    the contract ci_gate.sh exercises end-to-end."""
    from tpusnap.__main__ import main

    # (3) empty dir.
    assert main(["slo", "--check"]) == 3
    # Seed a fresh record through a real take.
    Snapshot.take(
        str(tmp_path / "s"),
        {"a": StateDict(w=np.arange(50000, dtype=np.float32))},
    )
    assert os.path.exists(slo_rank_path(0))
    # (0) healthy under a generous threshold.
    assert main(["slo", "--check", "--rpo", "3600"]) == 0
    # (2) stale-commit breach.
    rec = json.load(open(slo_rank_path(0)))
    rec["last_commit_ts"] = time.time() - 900
    json.dump(rec, open(slo_rank_path(0), "w"))
    assert main(["slo", "--check", "--rpo", "60"]) == 2
    # (3) RTO objective with no estimator verdict.
    assert main(["slo", "--check", "--rto", "60"]) == 3
    # Informational mode never gates (exit 0 once records exist).
    assert main(["slo", "--rpo", "60"]) == 0
    assert main(["slo", "--json"]) == 0


# ------------------------------------------------ prometheus + fleet fold


def test_prometheus_exposition_covers_slo_gauges(slo_env):
    """Acceptance: parse_prometheus_textfile covers the four new gauge
    families (plus the breach flag and fleet samples)."""
    sink = PrometheusTextfileSink(slo_env)
    state = {
        "rank": 0,
        "rpo_s": 12.5,
        "data_at_risk_bytes": 1 << 20,
        "estimated_rto_s": 42.0,
        "commit_interval_s": 30.0,
        "breach": {"rpo": True, "rto": False},
        "fleet": {
            "ranks": 4,
            "rpo_s": 99.0,
            "data_at_risk_bytes": 1 << 22,
            "estimated_rto_s": 50.0,
        },
    }
    sink.on_slo_update(state)
    text = open(sink.path(0)).read()
    parsed = parse_prometheus_textfile(text)
    from tpusnap.knobs import get_job_id

    job = get_job_id()
    for fam, local, fleet in (
        ("tpusnap_rpo_seconds", 12.5, 99.0),
        ("tpusnap_data_at_risk_bytes", float(1 << 20), float(1 << 22)),
        ("tpusnap_estimated_rto_seconds", 42.0, 50.0),
        ("tpusnap_commit_interval_seconds", 30.0, None),
    ):
        samples = parsed[fam]["samples"]
        assert parsed[fam]["type"] == "gauge"
        assert samples[f'{{job="{job}",rank="0"}}'] == local
        if fleet is not None:
            assert samples[f'{{job="{job}",rank="0",scope="fleet"}}'] == fleet
    breach = parsed["tpusnap_slo_breach"]["samples"]
    assert breach[f'{{job="{job}",objective="rpo",rank="0"}}'] == 1.0
    assert breach[f'{{job="{job}",objective="rto",rank="0"}}'] == 0.0


def test_fleet_fold_takes_worst_rank(slo_env):
    class FakeKV:
        def try_get_dir(self, prefix):
            return {
                f"{prefix}0": json.dumps(
                    {"slo": {"rpo_s": 3.0, "data_at_risk_bytes": 100}}
                ).encode(),
                f"{prefix}1": json.dumps(
                    {
                        "slo": {
                            "rpo_s": 9.0,
                            "data_at_risk_bytes": 50,
                            "estimated_rto_s": 7.0,
                        }
                    }
                ).encode(),
            }

    t, _clock, _ = _tracker()
    t.configure(rank=0, world_size=2)
    t._fold_fleet("take1", FakeKV())
    state = t.snapshot_state()
    assert state["fleet"] == {
        "ranks": 2,
        "rpo_s": 9.0,
        "data_at_risk_bytes": 100,
        "estimated_rto_s": 7.0,
    }


# ------------------------------------------------- end-to-end seam checks


def test_take_writes_sidecar_and_history_slo_section(slo_env, tmp_path):
    from tpusnap.history import load_history

    state = {"a": StateDict(w=np.arange(100000, dtype=np.float32))}
    Snapshot.take(str(tmp_path / "s1"), state)
    recs = read_slo_records()
    assert len(recs) == 1 and recs[0]["rank"] == 0
    rec = recs[0]
    assert rec["last_commit_ts"] is not None
    assert rec["snapshot_bytes"] == 400000
    assert rec["last_change_bytes"] == 400000  # full take: planned payload
    assert rec["data_at_risk_bytes"] == 0  # cleared at commit
    evs = [e for e in load_history() if e.get("kind") == "take"]
    assert evs and evs[-1]["slo"]["snapshot_bytes"] == 400000
    assert evs[-1]["commit_interval_s"] == evs[-1]["slo"]["commit_interval_s"]


def test_incremental_take_records_change_bytes(slo_env, tmp_path):
    state = {"a": StateDict(**{
        f"w{i}": np.arange(25000, dtype=np.float32) + i for i in range(4)
    })}
    Snapshot.take(str(tmp_path / "base"), state)
    # One of four arrays changes: the incremental commit's change bytes
    # must reflect the dual-hash skip evidence, not the full payload.
    state["a"]["w0"] = state["a"]["w0"] + 1.0
    Snapshot.take(
        str(tmp_path / "inc"), state, incremental_from=str(tmp_path / "base")
    )
    rec = read_slo_records()[0]
    total = 4 * 100000
    assert rec["snapshot_bytes"] == total
    assert 0 < rec["last_change_bytes"] < total


def test_async_take_anchors_commit(slo_env, tmp_path):
    pending = Snapshot.async_take(
        str(tmp_path / "s"),
        {"a": StateDict(w=np.arange(100000, dtype=np.float32))},
    )
    pending.wait()
    rec = read_slo_records()[0]
    assert rec["last_commit_ts"] is not None
    assert rec["data_at_risk_bytes"] == 0


def test_heartbeat_record_carries_slo_fields(slo_env, tmp_path):
    """The progress record's slo sub-dict (what `watch` renders and the
    fleet fold reads)."""
    from tpusnap.progress import read_progress_records, render_watch_table

    path = str(tmp_path / "s")
    with override_heartbeat_interval_s(0.01):
        Snapshot.take(
            path, {"a": StateDict(w=np.arange(200000, dtype=np.float32))}
        )
    recs = read_progress_records(path)
    assert recs and "slo" in recs[0]
    slo = recs[0]["slo"]
    assert "rpo_s" in slo and "data_at_risk_bytes" in slo
    table = render_watch_table(recs, committed=True, stall_flag_s=10)
    assert "at-risk" in table and "commit" in table


def test_watch_table_renders_exposure_columns():
    from tpusnap.progress import render_watch_table

    rec = {
        "rank": 0,
        "state": "running",
        "phase": "stage",
        "percent": 50.0,
        "mbps": 100.0,
        "beat_age_s": 0.1,
        "ts": 1000.0,
        "slo": {"rpo_s": 42.0, "data_at_risk_bytes": 3 * 1024**3},
    }
    table = render_watch_table([rec], committed=False, stall_flag_s=10, now=1000.0)
    assert "3.0G" in table and "42s" in table
    # Exposure grows with record staleness even when progress is frozen.
    table = render_watch_table([rec], committed=False, stall_flag_s=1e9, now=1010.0)
    assert "52s" in table


def test_record_step_rides_into_next_commit(slo_env, tmp_path):
    import tpusnap

    tpusnap.record_slo_step(12345)
    assert slo_mod.tracker().data_at_risk_bytes() == 12345
    Snapshot.take(
        str(tmp_path / "s"),
        {"a": StateDict(w=np.arange(1000, dtype=np.float32))},
    )
    assert slo_mod.tracker().data_at_risk_bytes() == 0


# -------------------------------------------------- crash-matrix validation

_CRASH_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

mode, path, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
state = {
    f"w{i}": np.random.default_rng(seed * 100 + i)
    .standard_normal((256, 256))
    .astype(np.float32)
    for i in range(8)
}
url = ("chaos+fs://" + path) if mode == "crash" else path
Snapshot.take(url, {"a": StateDict(**state)})
"""


def _crash_state_bytes():
    return 8 * 256 * 256 * 4


def _crash_state(seed):
    return {
        f"w{i}": np.random.default_rng(seed * 100 + i)
        .standard_normal((256, 256))
        .astype(np.float32)
        for i in range(8)
    }


def test_crash_matrix_data_at_risk_and_rto_accuracy(tmp_path):
    """Acceptance: SIGKILL a take mid-write and assert (a) the pre-kill
    exported ``tpusnap_data_at_risk_bytes`` matches the bytes the
    salvage/retake actually had to re-do (at-risk = salvaged + redone,
    the full interval change), and (b) a real measured restore falls
    within the documented ≤2x factor of the pre-crash
    ``tpusnap_estimated_rto_seconds``."""
    tele = str(tmp_path / "tele")
    mdir = str(tmp_path / "metrics")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_TELEMETRY_DIR=tele,
        TPUSNAP_METRICS_DIR=mdir,
        TPUSNAP_METRICS_EXPORT="prom",
        TPUSNAP_HEARTBEAT_INTERVAL_S="0.02",
        TPUSNAP_DISABLE_BATCHING="1",
    )
    env.pop("TPUSNAP_FAULT_SPEC", None)
    seed = 7
    nbytes = _crash_state_bytes()

    # 1. A committed base snapshot (the recovery point).
    base = str(tmp_path / "base")
    subprocess.run(
        [sys.executable, "-c", _CRASH_CHILD, "plain", base, str(seed)],
        check=True,
        env=env,
        timeout=180,
    )

    # 2. Three real restores feed the estimator's baseline (crash
    # recovery restores exactly this state from this storage).
    slo_mod.reset_tracker()
    with override_telemetry_dir(tele), override_metrics_dir(mdir):
        restore_walls = []
        for _ in range(3):
            target = {"a": StateDict(**_crash_state(seed))}
            t0 = time.perf_counter()
            Snapshot(base).restore(target)
            restore_walls.append(time.perf_counter() - t0)

        # 3. SIGKILL a take mid-write (chaos crash_after_op): the
        # pre-kill heartbeat ticks exported the SLO gauges to the prom
        # textfile at 20 ms cadence.
        torn = str(tmp_path / "torn")
        crash_env = dict(
            env,
            # Pin the job id: the prom filename carries it, and the
            # child's host-pid default is unknowable from here.
            TPUSNAP_JOB_ID="slocrash",
            TPUSNAP_FAULT_SPEC="latency_ms=150,crash_after_op=write:5",
            # Serialize the writes (one ~256 KB blob in flight at a
            # time): concurrent dispatch would complete all 8 writes in
            # one latency window and the SIGKILL would beat every
            # journal record flush — leaving nothing to salvage.
            TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES="300000",
        )
        r = subprocess.run(
            [sys.executable, "-c", _CRASH_CHILD, "crash", torn, str(seed)],
            capture_output=True,
            text=True,
            env=crash_env,
            timeout=180,
        )
        assert r.returncode == -signal.SIGKILL, r.stderr[-500:]

        prom = open(os.path.join(mdir, "tpusnap_slocrash_rank0.prom")).read()
        parsed = parse_prometheus_textfile(prom)
        at_risk = parsed["tpusnap_data_at_risk_bytes"]["samples"][
            '{job="slocrash",rank="0"}'
        ]
        est_samples = parsed.get("tpusnap_estimated_rto_seconds", {}).get(
            "samples", {}
        )
        assert est_samples, (
            "pre-crash prom carries no RTO estimate despite 3 restore "
            "events in history"
        )
        est_rto = est_samples['{job="slocrash",rank="0"}']

        # (a) Pre-kill data-at-risk = the take's full planned payload
        # (nothing was committed), which must equal what the salvage
        # retake re-does plus what it salvages — re-take the same state
        # and account for every byte.
        assert at_risk == nbytes
        from tpusnap import telemetry
        from tpusnap.knobs import override_batching_disabled

        telemetry.reset_global_counters()
        # Batching off like the crashed child: slab-batched retakes
        # always rewrite (no salvage), which would void the accounting.
        with override_batching_disabled(True):
            Snapshot.take(torn, {"a": StateDict(**_crash_state(seed))})
        # storage.bytes_written counts every payload byte the retake
        # processed (salvage skips happen below the counter, tallied in
        # salvage.bytes_salvaged): redone = written - salvaged, and
        # redone + salvaged must account for exactly the bytes the
        # pre-kill gauge declared at risk.
        written = telemetry.counter_value("storage.bytes_written")
        salvaged = telemetry.counter_value("salvage.bytes_salvaged")
        assert salvaged > 0, "crash at write:5 left nothing to salvage?"
        redone = written - salvaged
        assert redone > 0
        assert abs((redone + salvaged) - at_risk) / at_risk < 0.05

        # (b) A real measured restore within the documented ≤2x factor
        # of the pre-crash estimate (best of 3 — the estimator is a
        # median, one cold outlier must not fail the contract; the
        # 50 ms additive guard absorbs timer noise at this small scale).
        target = {"a": StateDict(**_crash_state(seed))}
        t0 = time.perf_counter()
        Snapshot(base).restore(target)
        measured = min(time.perf_counter() - t0, *restore_walls)
        assert measured <= 2.0 * est_rto + 0.05, (
            f"measured restore {measured:.3f}s vs pre-crash estimate "
            f"{est_rto:.3f}s — estimator overpromised by more than 2x"
        )
        assert est_rto <= 2.0 * measured + 0.05, (
            f"pre-crash estimate {est_rto:.3f}s vs measured {measured:.3f}s "
            "— estimator overestimated by more than 2x"
        )
    slo_mod.reset_tracker()
    install_env_sinks()
