"""Single-process end-to-end Snapshot.take/restore tests, mirroring the
reference's tests/test_snapshot.py:21-169."""

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusnap import PytreeState, RNGState, Snapshot, StateDict
from tpusnap.knobs import override_max_chunk_size_bytes
from tpusnap.manifest import (
    ChunkedTensorEntry,
    ObjectEntry,
    PrimitiveEntry,
    TensorEntry,
)


def _tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x = np.asarray(x)
        y = np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        assert x.tobytes() == y.tobytes()


def test_take_restore_state_dict(tmp_path, toggle_batching):
    app_state = {
        "state": StateDict(
            w=jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            b=np.random.default_rng(0).standard_normal(8).astype(np.float32),
            bf=jnp.ones((4, 4), dtype=jnp.bfloat16) * 1.5,
            epoch=7,
            lr=0.125,
            name="run/1%x",
            flag=True,
            blob=b"\x00\x01",
            nested={"a": [jnp.zeros(3), 2], "t": (jnp.ones(2), "s")},
        )
    }
    saved_w = np.asarray(app_state["state"]["w"]).copy()
    Snapshot.take(str(tmp_path / "snap"), app_state)

    dst = {
        "state": StateDict(
            w=jnp.zeros((8, 8), dtype=jnp.float32),
            b=np.zeros(8, dtype=np.float32),
            bf=jnp.zeros((4, 4), dtype=jnp.bfloat16),
            epoch=0,
            lr=0.0,
            name="",
            flag=False,
            blob=b"",
            nested={"a": [jnp.ones(3), 0], "t": (jnp.zeros(2), "")},
        )
    }
    Snapshot(str(tmp_path / "snap")).restore(dst)
    s = dst["state"]
    assert np.array_equal(np.asarray(s["w"]), saved_w)
    assert s["epoch"] == 7
    assert s["lr"] == 0.125
    assert s["name"] == "run/1%x"
    assert s["flag"] is True
    assert s["blob"] == b"\x00\x01"
    assert np.asarray(s["bf"]).tobytes() == np.asarray(app_state["state"]["bf"]).tobytes()
    assert isinstance(s["nested"]["t"], tuple)
    _tree_equal(s["nested"], app_state["state"]["nested"])


def test_take_restore_pytree_trainstate(tmp_path):
    """flax-style params + optax optimizer state round-trip."""
    import optax

    params = {
        "dense": {"kernel": jnp.ones((16, 4)), "bias": jnp.zeros(4)},
        "emb": jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
    }
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    app_state = {"train": PytreeState({"params": params, "opt": opt_state})}
    Snapshot.take(str(tmp_path / "snap"), app_state)

    params2 = jax.tree.map(lambda x: jnp.zeros_like(x), params)
    opt2 = tx.init(params2)
    dst_state = PytreeState({"params": params2, "opt": opt2})
    Snapshot(str(tmp_path / "snap")).restore({"train": dst_state})

    _tree_equal(dst_state.tree["params"], params)
    _tree_equal(dst_state.tree["opt"], opt_state)
    # NamedTuple structure preserved
    assert type(dst_state.tree["opt"]) is type(opt_state)


def test_chunked_roundtrip(tmp_path):
    with override_max_chunk_size_bytes(1024):
        arr = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
        app_state = {"s": StateDict(big=arr)}
        snap = Snapshot.take(str(tmp_path / "snap"), app_state)
        entry = snap.get_manifest()["0/s/big"]
        assert isinstance(entry, ChunkedTensorEntry)
        assert len(entry.chunks) == 16

        dst = {"s": StateDict(big=jnp.zeros((64, 64), dtype=jnp.float32))}
        snap.restore(dst)
        assert np.array_equal(np.asarray(dst["s"]["big"]), np.asarray(arr))


def test_manifest_entry_types(tmp_path):
    app_state = {
        "s": StateDict(
            t=jnp.ones(3), n=7, f=1.5, string="x", obj={1, 2, 3}
        )
    }
    snap = Snapshot.take(str(tmp_path / "snap"), app_state)
    manifest = snap.get_manifest()
    assert isinstance(manifest["0/s/t"], TensorEntry)
    assert isinstance(manifest["0/s/n"], PrimitiveEntry)
    assert isinstance(manifest["0/s/f"], PrimitiveEntry)
    assert isinstance(manifest["0/s/string"], PrimitiveEntry)
    assert isinstance(manifest["0/s/obj"], ObjectEntry)
    # primitives are inlined: restorable without touching their blobs
    dst = {"s": StateDict(t=jnp.zeros(3), n=0, f=0.0, string="", obj=set())}
    snap.restore(dst)
    assert dst["s"]["n"] == 7 and dst["s"]["obj"] == {1, 2, 3}


def test_structure_drift(tmp_path):
    """Loading into a state dict with extra/missing keys (reference
    tests/test_snapshot.py structure-drift case)."""
    app_state = {"s": StateDict(a=1, b=2)}
    snap = Snapshot.take(str(tmp_path / "snap"), app_state)
    dst = {"s": StateDict(a=0, c=99)}
    snap.restore(dst)
    assert dst["s"]["a"] == 1
    assert dst["s"]["b"] == 2  # appeared from snapshot
    assert "c" not in dst["s"]  # dropped: not in snapshot


def test_rng_state_invariance(tmp_path):
    rng = RNGState()
    app_state = {"rng": rng, "s": StateDict(x=1)}
    np.random.seed(1234)
    before = np.random.get_state()[1].copy()
    snap = Snapshot.take(str(tmp_path / "snap"), app_state)
    after = np.random.get_state()[1]
    assert np.array_equal(before, after), "take() perturbed RNG state"

    expected_draw = np.random.rand(4)  # the draw the restored RNG must repeat
    np.random.seed(999)
    snap.restore({"rng": RNGState(), "s": StateDict(x=0)})
    assert np.allclose(np.random.rand(4), expected_draw)


def test_restore_missing_snapshot_raises(tmp_path):
    with pytest.raises(RuntimeError, match="not a snapshot"):
        Snapshot(str(tmp_path / "nope")).restore({"s": StateDict()})


def test_take_restore_all_dtypes(tmp_path):
    from tpusnap.serialization import SUPPORTED_DTYPES, string_to_dtype

    state = {}
    for name in SUPPORTED_DTYPES:
        if name.startswith("complex"):
            arr = np.ones((3, 3), dtype=string_to_dtype(name)) * (1 + 2j)
        else:
            arr = np.ones((3, 3), dtype=string_to_dtype(name))
        state[name] = jnp.asarray(arr) if not name.startswith("complex") else arr
    app_state = {"d": StateDict(**state)}
    snap = Snapshot.take(str(tmp_path / "snap"), app_state)
    dst = {
        "d": StateDict(
            **{k: np.zeros((3, 3), dtype=np.asarray(v).dtype) for k, v in state.items()}
        )
    }
    snap.restore(dst)
    for name, orig in state.items():
        assert np.asarray(dst["d"][name]).tobytes() == np.asarray(orig).tobytes(), name


def test_metadata_file_is_last(tmp_path):
    """The metadata file marks commit: its presence implies all data files
    are complete."""
    snap_path = tmp_path / "snap"
    Snapshot.take(str(snap_path), {"s": StateDict(x=jnp.ones(4))})
    assert (snap_path / ".snapshot_metadata").exists()


class TestCustomArrayPrepareFunc:
    """Save-time array transform (reference _custom_tensor_prepare_func,
    snapshot.py:170-196): cast/quantize on save, restore honors the
    stored dtype."""

    def test_f32_to_bf16_on_save(self, tmp_path):
        import jax.numpy as jnp

        def cast_weights(path, arr, tracing):
            if path.endswith("/w"):
                return arr.astype(jnp.bfloat16)
            return arr

        w = np.linspace(-3, 3, 4096, dtype=np.float32)
        b = np.arange(16, dtype=np.float32)
        Snapshot.take(
            str(tmp_path / "s"),
            {"m": StateDict(w=w.copy(), b=b.copy())},
            _custom_array_prepare_func=cast_weights,
        )
        manifest = Snapshot(str(tmp_path / "s")).get_manifest()
        assert manifest["0/m/w"].dtype == "bfloat16"
        assert manifest["0/m/b"].dtype == "float32"

        # The entry dtype is honored on read (bytes deserialize as bf16 —
        # the precision loss proves it), then cast INTO the target's
        # dtype like the reference's tensor_copy (tensor.py:383-403):
        # an f32 training target receives the bf16-rounded values upcast.
        import ml_dtypes

        target = {"m": StateDict(w=np.zeros_like(w), b=np.zeros_like(b))}
        Snapshot(str(tmp_path / "s")).restore(target)
        restored_w = target["m"]["w"]
        assert restored_w.dtype == np.float32
        np.testing.assert_array_equal(
            restored_w, w.astype(ml_dtypes.bfloat16).astype(np.float32)
        )
        np.testing.assert_array_equal(target["m"]["b"], b)

    def test_chunked_transform(self, tmp_path):
        import jax.numpy as jnp

        from tpusnap.knobs import override_max_chunk_size_bytes

        arr = np.random.default_rng(0).standard_normal((64, 256)).astype(np.float32)
        with override_max_chunk_size_bytes(16 * 1024):
            Snapshot.take(
                str(tmp_path / "s"),
                {"m": StateDict(w=arr.copy())},
                _custom_array_prepare_func=lambda p, a, tracing: a.astype(
                    jnp.bfloat16
                ),
            )
        entry = Snapshot(str(tmp_path / "s")).get_manifest()["0/m/w"]
        assert entry.type == "ChunkedTensor" and entry.dtype == "bfloat16"
        assert len(entry.chunks) > 1
        out = Snapshot(str(tmp_path / "s")).read_object("0/m/w")
        assert str(out.dtype) == "bfloat16"
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), arr, atol=0.05
        )

    def test_shape_change_rejected(self, tmp_path):
        with pytest.raises(RuntimeError, match="shape"):
            Snapshot.take(
                str(tmp_path / "s"),
                {"m": StateDict(w=np.arange(100, dtype=np.float32))},
                _custom_array_prepare_func=lambda p, a, tracing: a[:50],
            )


def test_snapshot_handle_reuse_and_close(tmp_path):
    """restore/read_object/metadata reuse one event loop + storage
    plugin across calls; close() releases them and later calls
    transparently re-create them."""
    arrs = {f"w{i}": np.arange(1000, dtype=np.float32) + i for i in range(3)}
    Snapshot.take(str(tmp_path / "s"), {"m": StateDict(**arrs)})
    with Snapshot(str(tmp_path / "s")) as snap:
        first = snap._resources()
        for i in range(3):
            out = snap.read_object(f"0/m/w{i}")
            np.testing.assert_array_equal(out, arrs[f"w{i}"])
        assert snap._resources() == first  # same loop + plugin reused
        target = {"m": StateDict(**{k: np.zeros_like(v) for k, v in arrs.items()})}
        snap.restore(target)
        np.testing.assert_array_equal(target["m"]["w2"], arrs["w2"])
    # context exit closed the loop
    assert snap._cached_loop is None
    # calls after close still work (resources re-created)
    out = snap.read_object("0/m/w0")
    np.testing.assert_array_equal(out, arrs["w0"])
    snap.close()


class TestAsyncRestore:
    def test_round_trip(self, tmp_path):
        import numpy as np

        from tpusnap import Snapshot, StateDict

        src = StateDict(
            w=np.random.default_rng(0).standard_normal((512, 64)).astype(np.float32),
            step=9,
        )
        path = str(tmp_path / "s")
        Snapshot.take(path, {"app": src})
        target = {"app": StateDict(w=np.zeros((512, 64), np.float32), step=0)}
        pending = Snapshot(path).async_restore(target)
        pending.wait()
        assert pending.done()
        assert target["app"]["step"] == 9
        assert np.array_equal(target["app"]["w"], src["w"])

    def test_failure_reraises_from_wait(self, tmp_path):
        import numpy as np
        import pytest

        from tpusnap import Snapshot, StateDict

        path = str(tmp_path / "s")
        Snapshot.take(path, {"app": StateDict(w=np.ones(64, np.float32))})
        # Corrupt the snapshot's blob so the background read fails.
        for dirpath, _, files in __import__("os").walk(path):
            for f in files:
                if not f.startswith(".snapshot"):
                    full = __import__("os").path.join(dirpath, f)
                    with open(full, "r+b") as fh:
                        b = fh.read(1)
                        fh.seek(0)
                        fh.write(bytes([b[0] ^ 0xFF]))
        target = {"app": StateDict(w=np.zeros(64, np.float32))}
        pending = Snapshot(path).async_restore(target)
        with pytest.raises(Exception):
            pending.wait()

    def test_overlaps_with_other_work(self, tmp_path):
        """The call returns before the restore completes (the calling
        thread is free for compilation/data warmup)."""
        import numpy as np

        from tpusnap import Snapshot, StateDict

        src = StateDict(
            big=np.random.default_rng(1).standard_normal((4000, 1000)).astype(np.float32)
        )
        path = str(tmp_path / "s")
        Snapshot.take(path, {"app": src})
        target = {"app": StateDict(big=np.zeros((4000, 1000), np.float32))}
        pending = Snapshot(path).async_restore(target)
        # A 16 MB disk read cannot have completed in the microseconds
        # since the constructor returned: the work is actually
        # backgrounded, not run inline.
        assert not pending.done()
        pending.wait()
        assert np.array_equal(target["app"]["big"], src["big"])


class TestCastOnSave:
    def test_glob_cast_and_passthrough(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from tpusnap import Snapshot, StateDict
        from tpusnap.transforms import cast_on_save

        st = StateDict(
            kernel=np.random.default_rng(0).standard_normal((64, 32)).astype(np.float32),
            step_count=np.arange(8, dtype=np.int32),
        )
        path = str(tmp_path / "s")
        Snapshot.take(
            path,
            {"m": st},
            _custom_array_prepare_func=cast_on_save({"m/kernel": jnp.bfloat16}),
        )
        md = Snapshot(path).metadata
        assert md.manifest["0/m/kernel"].dtype == "bfloat16"
        assert md.manifest["0/m/step_count"].dtype == "int32"  # passthrough
        # Restore: stored bf16 lands in a bf16 target bit-exactly.
        import ml_dtypes

        target = {"m": StateDict(
            kernel=np.zeros((64, 32), dtype=ml_dtypes.bfloat16),
            step_count=np.zeros(8, np.int32),
        )}
        Snapshot(path).restore(target)
        expect = st["kernel"].astype(ml_dtypes.bfloat16)
        assert target["m"]["kernel"].tobytes() == expect.tobytes()
        assert np.array_equal(target["m"]["step_count"], st["step_count"])


class TestDtypeCastOnRestore:
    """A blob stored at reduced precision restores INTO a full-precision
    target upcast (the reference's tensor_copy casts into the target,
    io_preparers/tensor.py:383-403) — and vice versa; exact-dtype
    targets stay byte-exact in-place."""

    def _take_bf16(self, tmp_path):
        import jax.numpy as jnp

        from tpusnap.transforms import cast_on_save

        w = np.linspace(-2, 2, 4096).astype(np.float32).reshape(64, 64)
        path = str(tmp_path / "s")
        Snapshot.take(
            path,
            {"m": StateDict(w=w)},
            _custom_array_prepare_func=cast_on_save({"m/w": jnp.bfloat16}),
        )
        return path, w

    def test_upcast_into_f32_targets(self, tmp_path):
        import ml_dtypes

        path, w = self._take_bf16(tmp_path)
        expect = w.astype(ml_dtypes.bfloat16).astype(np.float32)

        tgt_np = {"m": StateDict(w=np.zeros((64, 64), np.float32))}
        Snapshot(path).restore(tgt_np)
        assert tgt_np["m"]["w"].dtype == np.float32
        assert np.array_equal(tgt_np["m"]["w"], expect)

        tgt_jax = {"m": StateDict(w=jnp.zeros((64, 64), jnp.float32))}
        Snapshot(path).restore(tgt_jax)
        assert tgt_jax["m"]["w"].dtype == jnp.float32
        assert np.array_equal(np.asarray(tgt_jax["m"]["w"]), expect)

    def test_upcast_under_memory_budget(self, tmp_path):
        """Tiled reads (mismatched-dtype target -> fresh host buffer)
        cast at completion too."""
        import ml_dtypes

        path, w = self._take_bf16(tmp_path)
        out = Snapshot(path).read_object(
            "0/m/w",
            obj_out=np.zeros((64, 64), np.float32),
            memory_budget_bytes=2048,
        )
        assert out.dtype == np.float32
        assert np.array_equal(out, w.astype(ml_dtypes.bfloat16).astype(np.float32))

    def test_no_target_keeps_stored_dtype(self, tmp_path):
        import ml_dtypes

        path, w = self._take_bf16(tmp_path)
        out = Snapshot(path).read_object("0/m/w")
        assert out.dtype == ml_dtypes.bfloat16


def test_edge_shapes_roundtrip(tmp_path):
    """0-d scalars, empty arrays, and zero-size axes survive every path
    (take/scrub/restore/read_object/incremental)."""
    from tpusnap import verify_snapshot

    cases = {
        "scalar0d": np.float32(3.5) * np.ones((), np.float32),
        "jscalar": jnp.asarray(2.5, jnp.float32),
        "empty": np.zeros((0,), np.float32),
        "zero_axis": np.zeros((4, 0, 8), np.float32),
        "one": np.ones((1,), np.float32),
    }
    path = str(tmp_path / "s")
    Snapshot.take(path, {"a": StateDict(**cases)})
    assert verify_snapshot(path).clean
    tgt = {
        "a": StateDict(
            **{k: np.zeros_like(np.asarray(v)) for k, v in cases.items()}
        )
    }
    Snapshot(path).restore(tgt)
    for k, v in cases.items():
        got = np.asarray(tgt["a"][k])
        assert got.shape == np.asarray(v).shape, k
        assert got.tobytes() == np.asarray(v).tobytes(), k
        out = Snapshot(path).read_object(f"0/a/{k}")
        assert np.asarray(out).shape == np.asarray(v).shape, k
    inc = str(tmp_path / "s2")
    Snapshot.take(inc, {"a": StateDict(**cases)}, incremental_from=path)
    assert verify_snapshot(inc).clean


def test_load_snapshot_without_program(tmp_path):
    """load_snapshot: the whole app state back as plain host structures,
    no statefuls or targets required (debugging/migration path)."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("x", "y"))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x", "y"))
    w = jax.device_put(jnp.arange(32 * 32, dtype=jnp.float32).reshape(32, 32), sh)
    st = StateDict(
        dense=np.random.default_rng(0).standard_normal((64, 8)).astype(np.float32),
        step=5,
        nested={"lr": 0.5, "l": [1, 2]},
    )
    path = str(tmp_path / "s")
    Snapshot.take(path, {"m": PytreeState({"w": w}), "t": st})

    from tpusnap import load_snapshot

    out = load_snapshot(path)
    assert set(out) == {"m", "t"}
    assert np.array_equal(out["m"]["w"], np.asarray(w))  # sharded -> dense
    assert np.array_equal(out["t"]["dense"], st["dense"])
    assert out["t"]["step"] == 5
    assert out["t"]["nested"] == {"lr": 0.5, "l": [1, 2]}
    # Budgeted load works too.
    out2 = load_snapshot(path, memory_budget_bytes=16 << 20)
    assert np.array_equal(out2["t"]["dense"], st["dense"])
