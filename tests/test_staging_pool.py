"""Staging-buffer pool (tpusnap/_staging_pool.py): the async-clone
warm-page reuse and its safety properties — exact-size reuse, oldest-
first eviction at the cap, leak-proof outstanding tracking, and
non-pool buffers being ignored."""

import time

import numpy as np
import pytest

import tpusnap._staging_pool as pool


@pytest.fixture(autouse=True)
def _fresh_pool():
    pool.clear()
    yield
    pool.clear()


def test_exact_size_reuse():
    a = pool.acquire(1 << 20)
    ptr = a.ctypes.data
    assert pool.release(a) is True
    b = pool.acquire(1 << 20)
    assert b.ctypes.data == ptr  # same (warm) buffer handed back
    # A different size misses and allocates fresh.
    c = pool.acquire(2 << 20)
    assert c.ctypes.data != ptr


def test_release_ignores_foreign_buffers():
    user = np.zeros(1 << 20, np.uint8)
    assert pool.release(user) is False
    assert pool.release(memoryview(user)) is False
    assert pool.release(b"bytes") is False


def test_cap_evicts_oldest_sizes(monkeypatch):
    monkeypatch.setenv("TPUSNAP_STAGING_POOL_BYTES", str(3 << 20))
    old = pool.acquire(2 << 20)
    old_ptr = old.ctypes.data
    assert pool.release(old) is True
    # A new size that would exceed the cap evicts the OLD entry instead
    # of being dropped — shape changes age stale sizes out.
    new = pool.acquire(2 << 20 | 4096)
    assert pool.release(new) is True
    reacquired_old = pool.acquire(2 << 20)
    assert reacquired_old.ctypes.data != old_ptr  # old was evicted

    # Buffers above the cap are never retained.
    monkeypatch.setenv("TPUSNAP_STAGING_POOL_BYTES", str(1 << 20))
    big = pool.acquire(2 << 20)
    assert pool.release(big) is False


def test_dropped_buffers_do_not_leak_tracking():
    import time

    a = pool.acquire(1 << 20)
    a_id = id(a)
    del a  # abort path: buffer garbage-collected without release()
    # Each acquire prunes dead outstanding entries. The probe buffer
    # must be HELD ALIVE through the check: a discarded acquire result
    # is freed instantly and the allocator recycles the just-freed
    # object address — often a_id itself — manufacturing a fresh dead
    # entry at the very key under test. Retry: a concurrent background
    # thread (async snapshots draining from earlier tests) can do the
    # same transiently; only a PERSISTENT dead entry is a leak.
    for _ in range(5):
        probe = pool.acquire(4096)
        ref = pool._outstanding.get(a_id)
        ok = ref is None or ref() is not None
        pool.release(probe)
        del probe
        if ok:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("dropped buffer leaked in _outstanding")


def test_double_release_is_inert():
    a = pool.acquire(1 << 20)
    assert pool.release(a) is True
    # Second release of the same (now-free) buffer must not double-add.
    assert pool.release(a) is False
    assert pool.free_bytes() == 1 << 20


def test_async_take_loop_reuses_buffers(tmp_path):
    """End to end: the second async take's clones come from the pool.
    Clone mode (``TPUSNAP_ASYNC_COW=0``): the default COW staging
    clones nothing, so there is no pool traffic to test there."""
    import tpusnap._staging_pool as sp
    from tpusnap import PytreeState, Snapshot
    from tpusnap.knobs import override_async_cow

    state = {
        f"w{i}": np.random.default_rng(i).standard_normal(1 << 17).astype(np.float32)
        for i in range(3)
    }  # 512 KiB each — above the pool's reuse floor, below slab batching? (they batch; members release too)
    take_bytes = sum(a.nbytes for a in state.values())  # one take's clones
    with override_async_cow(False):
        Snapshot.async_take(
            str(tmp_path / "s0"), {"m": PytreeState(state)}
        ).wait()
        # Clone releases trail wait() on the writer thread (release fires
        # per buffer inside the write pipeline) — settle before sampling
        # so the growth bound below is measured, not raced.
        deadline = time.monotonic() + 5.0
        free_after_first = sp.free_bytes()
        while free_after_first < take_bytes and time.monotonic() < deadline:
            time.sleep(0.01)
            free_after_first = sp.free_bytes()
        assert free_after_first > 0  # clones returned to the pool
        from tpusnap import telemetry

        hits_before = telemetry.counter_value("staging_pool.hits")
        Snapshot.async_take(
            str(tmp_path / "s1"), {"m": PytreeState(state)}
        ).wait()
        # Steady state: the second take's clones come back warm from the
        # pool. (Exact free_bytes equality is scheduler-timing dependent —
        # an acquire racing the previous window's release may allocate one
        # extra buffer — so assert reuse happened and growth stays bounded
        # by one take's worth of clone bytes, rather than byte-exact
        # stasis. The bound is anchored to take_bytes, not the first
        # sample: free_after_first itself can catch a subset of the
        # releases in flight.)
        assert telemetry.counter_value("staging_pool.hits") > hits_before
        assert sp.free_bytes() <= free_after_first + take_bytes
    # Both snapshots independently restore bit-exact.
    for s in ("s0", "s1"):
        tgt = {"m": PytreeState({k: np.zeros_like(v) for k, v in state.items()})}
        Snapshot(str(tmp_path / s)).restore(tgt)
        for k, v in state.items():
            assert np.array_equal(tgt["m"].tree[k], v), (s, k)
