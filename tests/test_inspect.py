"""Snapshot inspection + integrity scrub (tpusnap/inspect.py, __main__.py).

Scrub-the-world coverage: a clean snapshot verifies end to end; flipping a
single byte in any blob class (dense, slab member, tile of a large array,
shard, chunk, object pickle) is detected and attributed to the logical
path; truncation and missing blobs are detected; the CLI surfaces it all
with the documented exit codes.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusnap import PytreeState, Snapshot, StateDict, verify_snapshot
from tpusnap.__main__ import main as cli_main
from tpusnap.inspect import entry_nbytes, iter_blobs
from tpusnap.knobs import (
    override_batching_disabled,
    override_tile_checksum_bytes,
)


def _state():
    rng = np.random.default_rng(0)
    return StateDict(
        dense=rng.standard_normal((256, 128)).astype(np.float32),
        small=rng.standard_normal(16).astype(np.float32),
        obj={"nested": [1, 2, 3]},
        step=7,
        lr=1e-3,
    )


def _flip_byte(root: str, relpath_substr: str, offset: int = 100) -> str:
    """Flip one byte of the first blob file whose path contains
    ``relpath_substr``; returns the file touched."""
    for dirpath, _, files in os.walk(root):
        for f in files:
            full = os.path.join(dirpath, f)
            rel = os.path.relpath(full, root)
            if relpath_substr in rel and not f.startswith(".snapshot"):
                with open(full, "r+b") as fh:
                    fh.seek(min(offset, os.path.getsize(full) - 1))
                    b = fh.read(1)
                    fh.seek(-1, os.SEEK_CUR)
                    fh.write(bytes([b[0] ^ 0xFF]))
                return rel
    raise AssertionError(f"no blob matching {relpath_substr!r} under {root}")


def test_clean_snapshot_verifies(tmp_path, toggle_batching):
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": _state()})
    report = verify_snapshot(path)
    assert report.clean
    assert report.corrupt == 0
    assert report.ok > 0
    assert report.bytes_verified >= 256 * 128 * 4
    # Snapshot.verify() is the same scrub.
    assert Snapshot(path).verify().clean


def test_corrupt_dense_blob_detected(tmp_path):
    path = str(tmp_path / "snap")
    with override_batching_disabled(True):
        Snapshot.take(path, {"app": _state()})
    _flip_byte(path, "dense")
    report = verify_snapshot(path)
    assert not report.clean
    assert report.corrupt == 1
    assert any("app/dense" in f.manifest_path for f in report.failures)


def test_corrupt_tile_pinpointed(tmp_path):
    """A large array carries tile-grain checksums; the scrub must flag
    exactly the corrupted tile (not the whole blob) and report its rows."""
    path = str(tmp_path / "snap")
    arr = np.random.default_rng(1).standard_normal((4096, 32)).astype(np.float32)
    with override_tile_checksum_bytes(64 * 1024), override_batching_disabled(
        True
    ):  # force many tiles, keep the blob un-slabbed
        Snapshot.take(path, {"app": StateDict(big=arr)})
    report = verify_snapshot(path)
    assert report.clean and report.ok > 4  # verified per tile
    _flip_byte(path, "big", offset=10)  # inside tile 0
    report = verify_snapshot(path)
    assert report.corrupt == 1
    assert "rows 0:" in report.failures[0].detail


def test_corrupt_slab_member_attributed(tmp_path):
    """Small arrays are packed into a batched/ slab; corruption inside the
    slab must be attributed to the member's logical path."""
    path = str(tmp_path / "snap")
    st = StateDict(
        a=np.arange(64, dtype=np.float32), b=np.arange(64, 128, dtype=np.float32)
    )
    Snapshot.take(path, {"app": st})
    manifest = Snapshot(path).get_manifest()
    slabbed = [
        p
        for p, e in manifest.items()
        if getattr(e, "location", "").startswith("batched/")
    ]
    if not slabbed:  # batching knob off in this config
        pytest.skip("no slab in this snapshot")
    _flip_byte(path, "batched/", offset=4)
    report = verify_snapshot(path)
    assert not report.clean
    assert any("app/" in f.manifest_path for f in report.failures)


def test_truncated_blob_detected(tmp_path):
    path = str(tmp_path / "snap")
    with override_batching_disabled(True):
        Snapshot.take(path, {"app": _state()})
    for dirpath, _, files in os.walk(path):
        for f in files:
            full = os.path.join(dirpath, f)
            if "dense" in os.path.relpath(full, path):
                with open(full, "r+b") as fh:
                    fh.truncate(os.path.getsize(full) // 2)
    report = verify_snapshot(path)
    assert not report.clean


def test_missing_blob_detected(tmp_path):
    path = str(tmp_path / "snap")
    with override_batching_disabled(True):
        Snapshot.take(path, {"app": _state()})
    for dirpath, _, files in os.walk(path):
        for f in files:
            full = os.path.join(dirpath, f)
            if "dense" in os.path.relpath(full, path):
                os.remove(full)
    report = verify_snapshot(path)
    assert not report.clean
    assert any("read failed" in f.detail for f in report.failures)


def test_sharded_snapshot_verifies_and_detects(tmp_path):
    """Sharded entries (NamedSharding over a mesh) verify per shard."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("x", "y"))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("x", "y")
    )
    arr = jax.device_put(
        jnp.arange(64 * 64, dtype=jnp.float32).reshape(64, 64), sharding
    )
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": PytreeState({"w": arr})})
    report = verify_snapshot(path)
    assert report.clean and report.ok >= 4  # one range per shard minimum
    _flip_byte(path, "sharded", offset=8)
    report = verify_snapshot(path)
    assert not report.clean


def test_entry_nbytes_and_iter_blobs(tmp_path):
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": _state()})
    md = Snapshot(path).metadata
    blobs = list(iter_blobs(md.manifest))
    assert blobs, "manifest yields no blobs"
    # every blob belongs to a manifest entry and has a checksum recorded
    assert all(b.checksum for b in blobs)
    total = sum(
        entry_nbytes(e)
        for e in md.manifest.values()
    )
    assert total >= 256 * 128 * 4


def test_cli_info_ls_cat_verify(tmp_path, capsys):
    path = str(tmp_path / "snap")
    with override_batching_disabled(True):
        Snapshot.take(path, {"app": _state()})

    assert cli_main(["info", path]) == 0
    out = capsys.readouterr().out
    assert "world_size:  1" in out and "payload:" in out

    assert cli_main(["ls", "-l", path]) == 0
    out = capsys.readouterr().out
    assert "0/app/dense" in out and "tensor" in out

    assert cli_main(["cat", path, "0/app/step"]) == 0
    assert "7" in capsys.readouterr().out

    assert cli_main(["verify", path]) == 0
    assert "0 corrupt" in capsys.readouterr().out

    _flip_byte(path, "dense")
    assert cli_main(["verify", path]) == 2
    err = capsys.readouterr()
    assert "CORRUPT" in err.err

    assert cli_main(["info", str(tmp_path / "nosnap")]) == 1
    assert "error:" in capsys.readouterr().err

    # usage errors exit 1 (argparse's default of 2 would collide with
    # "2 = corruption found"); --help stays 0
    assert cli_main(["verify", "--bogus-flag", path]) == 1
    capsys.readouterr()
    assert cli_main(["--help"]) == 0
    capsys.readouterr()


def test_cli_module_invocation(tmp_path):
    """`python -m tpusnap verify` works as a real subprocess entry point."""
    import subprocess

    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": _state()})
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tpusnap", "verify", path],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "0 corrupt" in proc.stdout


def _world_take_for_scrub(snap_dir):
    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    # Rank-distinct per-rank state plus a replicated value.
    state = StateDict(
        local=np.full((64, 8), comm.rank, dtype=np.float32),
        shared=np.arange(128, dtype=np.float32),
    )
    Snapshot.take(snap_dir, {"app": state}, replicated=["**/shared"])


def test_multiprocess_snapshot_scrubs_clean_and_detects(tmp_path):
    """A world-2 snapshot (per-rank + replicated entries) scrubs clean
    from a single process; corruption in a rank-1 blob is detected and
    attributed to the '1/...' manifest path."""
    from tpusnap.test_utils import run_subprocess_world

    path = str(tmp_path / "snap")
    run_subprocess_world(_world_take_for_scrub, world_size=2, args=[path])
    report = verify_snapshot(path)
    assert report.clean
    md = Snapshot(path).metadata
    assert md.world_size == 2
    assert "1/app/local" in md.manifest  # rank-1 entries present

    _flip_byte(path, "1/app/local")
    report = verify_snapshot(path)
    assert not report.clean
    assert any(f.manifest_path.startswith("1/") for f in report.failures)


def test_scrub_concurrency_knob(tmp_path, monkeypatch):
    """TPUSNAP_SCRUB_CONCURRENCY=1 degrades to serial and still verifies."""
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": _state()})
    monkeypatch.setenv("TPUSNAP_SCRUB_CONCURRENCY", "1")
    assert verify_snapshot(path).clean
    monkeypatch.setenv("TPUSNAP_SCRUB_CONCURRENCY", "16")
    assert verify_snapshot(path).clean


def test_diff_snapshots(tmp_path, capsys):
    """Manifest-only diff: identical/changed/added/removed classification
    across batching modes and incremental references (content identity is
    location-independent — a slab-repacked or base-referenced blob with
    the same bytes diffs as identical)."""
    from tpusnap.__main__ import main as cli_main
    from tpusnap.inspect import diff_snapshots

    st = _state()
    a = str(tmp_path / "a")
    with override_batching_disabled(True):
        Snapshot.take(a, {"app": st})

    # Same content, different physical layout: batching ON + incremental.
    b = str(tmp_path / "b")
    Snapshot.take(b, {"app": st}, incremental_from=a)
    d = diff_snapshots(a, b)
    assert d.same, d.summary()

    # Change one value, drop one key, add one key.
    st2 = _state()
    st2["dense"] = st2["dense"] + 1.0
    del st2["small"]
    st2["extra"] = np.ones(8, np.float32)
    c = str(tmp_path / "c")
    with override_batching_disabled(True):
        Snapshot.take(c, {"app": st2})
    d = diff_snapshots(a, c)
    assert "0/app/dense" in d.changed
    assert "0/app/small" in d.removed
    assert "0/app/extra" in d.added
    assert not d.same

    assert cli_main(["diff", a, b]) == 0
    assert "0 changed" in capsys.readouterr().out
    assert cli_main(["diff", "-q", a, c]) == 2
    out = capsys.readouterr().out
    assert "1 changed, 1 added, 1 removed" in out


def test_diff_undecidable_cases(tmp_path, capsys):
    """Checksum-less snapshots and incomparable layouts are 'undecidable'
    (exit 3), never claimed identical or different."""
    from tpusnap.__main__ import main as cli_main
    from tpusnap.inspect import diff_snapshots
    from tpusnap.knobs import (
        override_checksum_disabled,
        override_max_chunk_size_bytes,
    )

    st = _state()
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    with override_checksum_disabled(True):
        Snapshot.take(a, {"app": st})
        Snapshot.take(b, {"app": st})
    d = diff_snapshots(a, b)
    assert not d.same and not d.differs and d.unknown
    assert cli_main(["diff", "-q", a, b]) == 3
    capsys.readouterr()

    # Same bytes, different chunk geometry: row-chunk checksums FOLD to
    # the whole-array value (CRC combine), so this is provably identical
    # — tile-grain incremental takes re-chunk arrays on the base's tile
    # grid and must still diff as identical, not undecidable.
    big = np.random.default_rng(0).standard_normal((64, 64)).astype(np.float32)
    c1, c2 = str(tmp_path / "c1"), str(tmp_path / "c2")
    with override_batching_disabled(True):
        with override_max_chunk_size_bytes(4 * 1024):
            Snapshot.take(c1, {"app": StateDict(big=big)})
        with override_max_chunk_size_bytes(2 * 1024):
            Snapshot.take(c2, {"app": StateDict(big=big)})
    d = diff_snapshots(c1, c2)
    assert "0/app/big" in d.identical and not d.differs
    # ...and a changed value across different chunk geometries is
    # provably CHANGED, not undecidable.
    big2 = big.copy()
    big2[17, 3] += 1.0
    c2b = str(tmp_path / "c2b")
    with override_batching_disabled(True):
        with override_max_chunk_size_bytes(2 * 1024):
            Snapshot.take(c2b, {"app": StateDict(big=big2)})
    d = diff_snapshots(c1, c2b)
    assert "0/app/big" in d.changed

    # Different dtype at the same path: provably changed even across
    # layouts.
    c3 = str(tmp_path / "c3")
    with override_batching_disabled(True):
        Snapshot.take(c3, {"app": StateDict(big=big.astype(np.float64))})
    d = diff_snapshots(c1, c3)
    assert "0/app/big" in d.changed


# ------------------------------------------------------------- round 4:
# ADVICE fixes — verify exit 3, recorded base roots, async_restore guard


def test_cli_verify_exit3_when_nothing_verifiable(tmp_path, capsys):
    """`verify` exiting 0 when every blob is UNVERIFIED would let
    scripts mistake 'nothing was checkable' for 'verified clean'
    (ADVICE r3): a checksum-less snapshot must exit 3, mirroring diff's
    undecidable convention."""
    from tpusnap.knobs import override_checksum_disabled

    path = str(tmp_path / "s")
    with override_checksum_disabled(True):
        Snapshot.take(path, {"app": StateDict(w=np.arange(64, dtype=np.float32))})
    assert cli_main(["verify", path]) == 3
    err = capsys.readouterr().err
    assert "nothing verified" in err
    # A normal snapshot still exits 0 (and a corrupt one 2 — covered by
    # test_cli_info_ls_cat_verify).
    good = str(tmp_path / "g")
    Snapshot.take(good, {"app": StateDict(w=np.arange(64, dtype=np.float32))})
    capsys.readouterr()
    assert cli_main(["verify", good]) == 0


def test_base_roots_recorded_and_resolve_numeric_dirs(tmp_path):
    """A base path with a purely NUMERIC intermediate directory
    ("exp/1000/final") defeats grammar parsing (ADVICE r3) — the take
    now records metadata.base_roots, and retention/info/materialize
    resolve through it instead of guessing."""
    from tpusnap.inspect import base_root_of_location
    from tpusnap.retention import _referenced_bases

    base = str(tmp_path / "exp" / "1000" / "final")
    inc = str(tmp_path / "exp" / "1000" / "cont")
    st = StateDict(w=np.random.default_rng(0).standard_normal(4096).astype(np.float32))
    Snapshot.take(base, {"app": st})
    Snapshot.take(inc, {"app": st}, incremental_from=base)
    md = Snapshot(inc).metadata
    assert md.base_roots == ["../final"]
    # Grammar parsing alone is fooled by the advisor's exact hazard — a
    # MULTI-segment base path with an interior numeric directory — while
    # the recorded roots resolve it exactly.
    loc = "../exp/1000/final/0/w"
    assert base_root_of_location(loc) == "../exp"  # grammar guesses wrong
    assert (
        base_root_of_location(loc, known_roots=["../exp/1000/final"])
        == "../exp/1000/final"
    )
    # retention resolves through the recorded roots.
    bases = _referenced_bases(inc)
    assert bases == [os.path.abspath(base)]
    # materialize clears base_roots once self-contained.
    from tpusnap.inspect import materialize_snapshot

    materialize_snapshot(inc)
    assert Snapshot(inc).metadata.base_roots is None
    assert verify_snapshot(inc).clean


def test_chained_base_roots_accumulate(tmp_path):
    """A chain's 2nd increment references BOTH earlier snapshots; its
    recorded roots must list each one it actually points into."""
    s0, s1, s2 = (str(tmp_path / f"step_{i}") for i in range(3))
    rng = np.random.default_rng(1)
    a = rng.standard_normal(4096).astype(np.float32)
    b = rng.standard_normal(4096).astype(np.float32)
    Snapshot.take(s0, {"app": StateDict(a=a, b=b)})
    Snapshot.take(s1, {"app": StateDict(a=a, b=b + 1)}, incremental_from=s0)
    Snapshot.take(s2, {"app": StateDict(a=a, b=b + 1)}, incremental_from=s1)
    md = Snapshot(s2).metadata
    assert md.base_roots == ["../step_0", "../step_1"]


def test_async_restore_rejects_collective_stateful(tmp_path):
    """A stateful declaring load_requires_collectives=True must be
    rejected by async_restore (collectives on the background thread run
    unordered across ranks) and still restore fine synchronously."""
    import pytest

    class CollectiveStateful(StateDict):
        load_requires_collectives = True

    path = str(tmp_path / "s")
    Snapshot.take(path, {"m": CollectiveStateful(w=np.arange(8, dtype=np.float32))})
    target = {"m": CollectiveStateful(w=np.zeros(8, np.float32))}
    with pytest.raises(ValueError, match="load_requires_collectives"):
        Snapshot(path).async_restore(target)
    Snapshot(path).restore(target, per_key_barrier=True)
    assert np.array_equal(target["m"]["w"], np.arange(8, dtype=np.float32))
