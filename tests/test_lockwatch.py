"""The runtime lock-order watchdog (``tpusnap.devtools.lockwatch``):
cycle detection on a deliberate AB/BA pattern across two threads (the
PR 6 deadlock shape), trylock semantics, RLock re-entry, held-across-
I/O notes, and the global ``threading.Lock`` patch's compatibility with
the stdlib synchronization primitives the package leans on.

The synthetic-cycle tests use a PRIVATE :class:`LockOrderWatch` over
``raw_lock()`` primitives so the session-global graph (tier-1 runs with
``TPUSNAP_LOCKCHECK=1`` and fails on any cycle) stays clean."""

import queue
import threading
import time

import pytest

from tpusnap.devtools import lockwatch
from tpusnap.devtools.lockwatch import LockOrderWatch


def _run_in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


def test_ab_ba_cycle_two_threads_names_locks_and_sites():
    """The acceptance shape: two threads acquire two locks in opposite
    orders (sequentially — the graph records POTENTIAL deadlocks, no
    lucky schedule needed) and the cycle report names both locks and
    both acquisition sites."""
    watch = LockOrderWatch()
    lock_a = watch.wrap(lockwatch.raw_lock(), "A")
    lock_b = watch.wrap(lockwatch.raw_lock(), "B")

    def thread_one():
        with lock_a:
            with lock_b:  # A -> B
                pass

    def thread_two():
        with lock_b:
            with lock_a:  # B -> A
                pass

    _run_in_thread(thread_one)
    _run_in_thread(thread_two)

    cycles = watch.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]["locks"]) == {"A", "B"}
    # Both edges carry held-at/acquired-at evidence from THIS file.
    for edge in cycles[0]["edges"]:
        assert "test_lockwatch.py:" in edge["held_at"]
        assert "test_lockwatch.py:" in edge["acquired_at"]
    rendered = watch.render()
    assert "CYCLE" in rendered and "A" in rendered and "B" in rendered


def test_consistent_order_is_not_a_cycle():
    watch = LockOrderWatch()
    lock_a = watch.wrap(lockwatch.raw_lock(), "A")
    lock_b = watch.wrap(lockwatch.raw_lock(), "B")
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert watch.cycles() == []
    assert watch.report()["edges"] == 1


def test_three_lock_cycle_detected():
    """Longer cycles (A→B→C→A) are potential deadlocks too — the SCC
    pass catches what a pairwise AB/BA scan would miss."""
    watch = LockOrderWatch()
    locks = {n: watch.wrap(lockwatch.raw_lock(), n) for n in "ABC"}
    for first, second in [("A", "B"), ("B", "C"), ("C", "A")]:
        with locks[first]:
            with locks[second]:
                pass
    cycles = watch.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]["locks"]) == {"A", "B", "C"}


def test_trylock_adds_no_order_edge():
    """A non-blocking acquire cannot wait, so it cannot deadlock: no
    edge (lockdep's trylock rule) — but the lock still joins the held
    stack, so locks acquired UNDER it do edge from it."""
    watch = LockOrderWatch()
    lock_a = watch.wrap(lockwatch.raw_lock(), "A")
    lock_b = watch.wrap(lockwatch.raw_lock(), "B")
    with lock_a:
        assert lock_b.acquire(blocking=False)  # no A -> B edge
        lock_b.release()
    assert watch.report()["edges"] == 0
    # ...but a blocking acquire under a trylock still records.
    assert lock_a.acquire(blocking=False)
    with lock_b:  # A -> B via blocking acquire under held trylock
        pass
    lock_a.release()
    assert watch.report()["edges"] == 1


def test_rlock_reentry_is_one_hold():
    watch = LockOrderWatch()
    rlock = watch.wrap(lockwatch.raw_rlock(), "R")
    other = watch.wrap(lockwatch.raw_lock(), "L")
    with rlock:
        with rlock:  # re-entry: no self-edge, still one held entry
            with other:
                pass
    report = watch.report()
    assert report["edges"] == 1  # R -> L only
    assert watch.cycles() == []
    assert report["nested_same_site"] == {}


def test_io_hold_recorded_with_site_and_count():
    watch = LockOrderWatch()
    lock_a = watch.wrap(lockwatch.raw_lock(), "A")
    with lock_a:
        watch.note_blocking("storage_write")
        watch.note_blocking("storage_write")
    watch.note_blocking("storage_write")  # nothing held: not recorded
    holds = watch.report()["io_holds"]
    assert len(holds) == 1
    assert holds[0]["lock"] == "A"
    assert holds[0]["tag"] == "storage_write"
    assert holds[0]["count"] == 2
    assert "test_lockwatch.py:" in holds[0]["held_at"]


def test_wrap_dispatches_lock_vs_rlock():
    watch = LockOrderWatch()
    assert isinstance(
        watch.wrap(lockwatch.raw_lock(), "l"), lockwatch.TrackedLock
    )
    assert isinstance(
        watch.wrap(lockwatch.raw_rlock(), "r"), lockwatch.TrackedRLock
    )


# ------------------------------------------------- global install patch


@pytest.fixture()
def global_watch():
    """The session's active watch (tier-1 runs with TPUSNAP_LOCKCHECK=1
    installed by conftest/package import); installs a temporary one if
    the suite was launched with lockcheck disabled."""
    watch = lockwatch.active_watch()
    if watch is not None:
        yield watch
        return
    watch = lockwatch.install()
    try:
        yield watch
    finally:
        lockwatch.uninstall()


def test_threading_lock_is_tracked_and_edges_recorded(global_watch):
    lock_a = threading.Lock()
    lock_b = threading.RLock()
    assert isinstance(lock_a, lockwatch.TrackedLock)
    assert isinstance(lock_b, lockwatch.TrackedRLock)
    with lock_a:
        with lock_b:  # one consistent-order edge; never a cycle
            pass
    edges = global_watch._edges  # keyed by creation site
    assert any(
        "test_lockwatch.py" in a and "test_lockwatch.py" in b
        for (a, b) in edges
    )


def test_stdlib_primitives_survive_the_patch(global_watch):
    """Event/Condition/Queue are built on the patched factories; the
    proxies must keep the Condition protocol (full release across
    wait) consistent or the held stacks go stale."""
    event = threading.Event()
    event.set()
    assert event.wait(0.5)

    q = queue.Queue()
    q.put(42)
    assert q.get(timeout=1) == 42

    cond = threading.Condition()
    woke = []

    def waiter():
        with cond:
            woke.append(cond.wait(timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(timeout=10)
    assert not t.is_alive() and woke == [True]


def test_locked_and_context_protocol(global_watch):
    lock = threading.Lock()
    assert not lock.locked()
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_finalizer_executor_shutdown_never_waits_on_the_lock():
    """Regression for the watchdog's second catch: a GC finalizer
    calling ``executor.shutdown()`` BLOCKS on ``_shutdown_lock`` and
    can complete an AB/BA deadlock with two ``submit()``s (one holding
    its executor lock waiting for the global shutdown lock, the other
    holding the global lock when GC fires). The finalizer path of
    ``shutdown_plugin_executor`` must trylock: shut down when
    uncontended, skip (leave the executor to the exit reaper) when
    not — never wait."""
    from concurrent.futures import ThreadPoolExecutor

    from tpusnap.io_types import finalizer_close_scope, shutdown_plugin_executor

    # Uncontended: behaves like shutdown(wait=False) — flag set, queued
    # work still completes, no thread join.
    ex = ThreadPoolExecutor(1)
    fut = ex.submit(lambda: 42)
    with finalizer_close_scope():
        shutdown_plugin_executor(ex)
    assert ex._shutdown
    assert fut.result(timeout=10) == 42

    # Contended: returns immediately instead of blocking — the deadlock
    # scenario has another thread holding the shutdown lock forever.
    ex2 = ThreadPoolExecutor(1)
    assert ex2._shutdown_lock.acquire(timeout=5)
    try:
        done = threading.Event()

        def finalizer_path():
            with finalizer_close_scope():
                shutdown_plugin_executor(ex2)
            done.set()

        t = threading.Thread(target=finalizer_path)
        t.start()
        assert done.wait(timeout=10), (
            "finalizer shutdown blocked on a contended _shutdown_lock"
        )
        t.join(timeout=10)
        assert not ex2._shutdown  # skipped, not half-applied
    finally:
        ex2._shutdown_lock.release()
    ex2.shutdown(wait=True)
