"""Manifest model tests, mirroring the reference's
tests/test_manifest.py:38-120 round-trip coverage."""

import math

from tpusnap.manifest import (
    Chunk,
    ChunkedTensorEntry,
    DictEntry,
    ListEntry,
    ObjectEntry,
    OrderedDictEntry,
    PrimitiveEntry,
    Shard,
    ShardedEntry,
    SnapshotMetadata,
    TensorEntry,
    TupleEntry,
    is_container_entry,
    is_replicated,
)


def _sample_manifest():
    return {
        "0/model": DictEntry(keys=["w", "b", 7]),
        "0/model/w": TensorEntry(
            location="0/model/w",
            serializer="buffer_protocol",
            dtype="bfloat16",
            shape=[128, 256],
            replicated=False,
        ),
        "0/model/b": TensorEntry(
            location="batched/abc",
            serializer="buffer_protocol",
            dtype="float32",
            shape=[256],
            replicated=True,
            byte_range=[0, 1024],
        ),
        "0/model/7": PrimitiveEntry.from_object(3.14159),
        "0/opt": TupleEntry(),
        "0/opt/0": ObjectEntry(
            location="0/opt/0",
            serializer="pickle",
            obj_type="ScaleByAdamState",
            replicated=False,
        ),
        "0/big": ChunkedTensorEntry(
            dtype="float32",
            shape=[1000, 10],
            chunks=[
                Chunk(
                    offsets=[0, 0],
                    sizes=[500, 10],
                    tensor=TensorEntry(
                        location="0/big_0_0",
                        serializer="buffer_protocol",
                        dtype="float32",
                        shape=[500, 10],
                        replicated=False,
                    ),
                )
            ],
            replicated=False,
        ),
        "sharded/emb": ShardedEntry(
            shards=[
                Shard(
                    offsets=[0, 0],
                    sizes=[512, 64],
                    tensor=TensorEntry(
                        location="sharded/emb_0",
                        serializer="buffer_protocol",
                        dtype="float32",
                        shape=[512, 64],
                        replicated=False,
                    ),
                ),
                Shard(
                    offsets=[512, 0],
                    sizes=[512, 64],
                    tensor=TensorEntry(
                        location="sharded/emb_1",
                        serializer="buffer_protocol",
                        dtype="float32",
                        shape=[512, 64],
                        replicated=False,
                    ),
                ),
            ]
        ),
        "0/list": ListEntry(),
        "0/od": OrderedDictEntry(keys=["x"]),
    }


def test_metadata_yaml_roundtrip():
    md = SnapshotMetadata(version="0.1.0", world_size=4, manifest=_sample_manifest())
    s = md.to_yaml()
    md2 = SnapshotMetadata.from_yaml(s)
    assert md2.version == "0.1.0"
    assert md2.world_size == 4
    assert set(md2.manifest.keys()) == set(md.manifest.keys())
    for k in md.manifest:
        assert md.manifest[k] == md2.manifest[k], k


def test_primitive_float_bit_exact():
    for val in [0.1, math.pi, 1e-300, -0.0, 3.0]:
        e = PrimitiveEntry.from_object(val)
        roundtripped = e.get_value()
        assert math.copysign(1, roundtripped) == math.copysign(1, val)
        assert roundtripped == val or (math.isnan(val) and math.isnan(roundtripped))
        # bit-exactness via struct pack equality
        import struct

        assert struct.pack("<d", roundtripped) == struct.pack("<d", val)


def test_primitive_types():
    assert PrimitiveEntry.from_object(42).get_value() == 42
    assert PrimitiveEntry.from_object(True).get_value() is True
    assert PrimitiveEntry.from_object(False).get_value() is False
    assert PrimitiveEntry.from_object("hi/there%42").get_value() == "hi/there%42"
    assert PrimitiveEntry.from_object(b"\x00\xffbin").get_value() == b"\x00\xffbin"
    assert PrimitiveEntry.supported(1)
    assert PrimitiveEntry.supported("x")
    assert not PrimitiveEntry.supported([1])
    assert not PrimitiveEntry.supported(None)


def test_sharded_entry_infers_global_shape():
    e = _sample_manifest()["sharded/emb"]
    assert e.shape == [1024, 64]
    assert e.dtype == "float32"


def test_is_replicated_and_container():
    m = _sample_manifest()
    assert is_replicated(m["0/model/b"])
    assert not is_replicated(m["0/model/w"])
    assert not is_replicated(m["0/model"])
    assert is_container_entry(m["0/model"])
    assert is_container_entry(m["0/opt"])
    assert is_container_entry(m["0/list"])
    assert is_container_entry(m["0/od"])
    assert not is_container_entry(m["0/model/w"])
