"""Device→host transfer overlap: the design claim behind prepare-time
``copy_to_host_async`` enqueue (io_preparers/array.py enqueue_dtoh) is
that N arrays' DMAs overlap, so staging wall-clock approaches the max,
not the sum, of the transfers — the role the reference's thread-pooled
GIL-released ``Tensor.to("cpu")`` plays (io_preparers/tensor.py:247-254).

This must be measured against a REAL accelerator (the CPU backend's
"transfer" is a memcpy with nothing to overlap), so the probe runs in a
subprocess that does NOT inherit the suite's forced-CPU platform; it
skips when no non-CPU device is reachable.
"""

import json
import os
import sys

import pytest

_PROBE = r"""
import json, time
import jax, jax.numpy as jnp
import numpy as np

dev = jax.devices()[0]
if dev.platform == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    raise SystemExit(0)

N = 4
NB = 2 * 1024 * 1024 // 4  # 2 MB of f32 per array (tunnel-friendly)

def fresh(tag):
    arrs = [
        jax.device_put(jnp.arange(NB, dtype=jnp.float32) + tag * 1000 + i, dev)
        for i in range(N)
    ]
    jax.block_until_ready(arrs)
    return arrs

np.asarray(fresh(9)[0])  # warm up the transfer path

best_ratio = None
for attempt in range(3):
    arrs = fresh(attempt * 2)
    t0 = time.perf_counter()
    for a in arrs:
        np.asarray(a)  # serial: each transfer starts when requested
    t_seq = time.perf_counter() - t0

    arrs = fresh(attempt * 2 + 1)
    t0 = time.perf_counter()
    for a in arrs:
        a.copy_to_host_async()  # all DMAs in flight before any wait
    for a in arrs:
        np.asarray(a)
    t_overlap = time.perf_counter() - t0
    ratio = t_overlap / t_seq
    best_ratio = ratio if best_ratio is None else min(best_ratio, ratio)

print(json.dumps({"ratio": best_ratio}))
"""


def test_copy_to_host_async_overlaps_transfers():
    from tpusnap._subproc import run_hard_timeout

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the real backend register
    env.pop("XLA_FLAGS", None)
    # run_hard_timeout, NOT subprocess.run(capture_output=...): the
    # PJRT tunnel helper survives a child kill holding the captured
    # pipes open, which wedged a full-suite run >60 min in round 4.
    proc = run_hard_timeout(
        [sys.executable, "-c", _PROBE], timeout_s=150, env=env, retries=1
    )
    if proc.timed_out:
        # The real-TPU tunnel can hang under contention; that's an
        # environment condition, not an overlap regression.
        pytest.skip("accelerator probe timed out (tunnel busy/unreachable)")
    if proc.returncode != 0:
        pytest.skip(f"accelerator probe failed: {proc.stderr[-500:]}")
    lines = proc.stdout.strip().splitlines()
    if not lines:
        pytest.skip("accelerator probe produced no output")
    result = json.loads(lines[-1])
    if "skip" in result:
        pytest.skip(result["skip"])
    # Pre-enqueued DMAs must beat serial request-then-wait transfers.
    # (Measured ~0.79 on a tunneled v5e chip; real HBM DMA overlaps far
    # more. 0.97 catches the regression mode: enqueue being a no-op that
    # serializes everything behind dispatch.)
    assert result["ratio"] < 0.97, (
        f"copy_to_host_async enqueue shows no overlap: "
        f"ratio={result['ratio']:.2f} (overlapped/serial)"
    )
