"""Live observability tests: heartbeat throttling and the stall
watchdog on a fake clock (no sleeps), straggler skew math, the `stall`
fault kind, the `watch` CLI against an in-flight take, restore traces +
`trace --restore`, and the 2-process stall-attribution acceptance test.
"""

import json
import logging
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import FaultPlan, PytreeState, Snapshot
from tpusnap import telemetry
from tpusnap.dist_store import MemoryKVStore
from tpusnap.knobs import override_telemetry_dir, override_telemetry_enabled
from tpusnap.progress import (
    PROGRESS_DIR,
    ProgressMonitor,
    local_root_of,
    read_progress_records,
    render_watch_table,
    restore_trace_dir,
)
from tpusnap.telemetry import TakeTelemetry, rollup_summaries

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _monitor(rec, tmp_path, clk, **kw):
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("stall_deadline_s", 5.0)
    return ProgressMonitor(
        rec,
        rank=kw.pop("rank", 0),
        world_size=kw.pop("world_size", 1),
        take_id="t0",
        kv=kw.pop("kv", MemoryKVStore()),
        local_dir=str(tmp_path),
        clock=clk,
        wall_clock=lambda: 1_000_000.0,
        thread=False,
        **kw,
    )


# ------------------------------------------------- heartbeat throttling


def test_heartbeat_time_and_delta_throttled(tmp_path):
    rec = TakeTelemetry(rank=0, enabled=True)
    clk = FakeClock()
    mon = _monitor(rec, tmp_path, clk)
    mon.set_bytes_planned(100)

    mon.tick()  # first observation publishes immediately
    assert mon.published == 1
    mon.tick()  # nothing changed, interval not elapsed
    assert mon.published == 1
    clk.t += 1.5
    mon.tick()  # interval elapsed but NOTHING changed: delta throttle
    assert mon.published == 1
    telemetry.incr("storage.bytes_written", 60, rec=rec)
    mon.tick()  # changed + due -> publish
    assert mon.published == 2
    telemetry.incr("storage.bytes_written", 40, rec=rec)
    mon.tick()  # changed but within the interval: time throttle
    assert mon.published == 2
    clk.t += 1.1
    mon.tick()
    assert mon.published == 3
    # Keep-alive: with no change at all, a record still goes out every
    # 10 intervals so watchers can tell idle-alive from dead.
    clk.t += 10.1
    mon.tick()
    assert mon.published == 4
    rec.finalize()


def test_heartbeat_record_contents_and_final_commit(tmp_path):
    rec = TakeTelemetry(rank=3, enabled=True)
    clk = FakeClock()
    kv = MemoryKVStore()
    mon = _monitor(rec, tmp_path, clk, rank=3, world_size=4, kv=kv)
    mon.set_bytes_planned(200)
    telemetry.incr("storage.bytes_written", 50, rec=rec)
    mon.tick()
    recs = read_progress_records(str(tmp_path))
    assert len(recs) == 1
    r = recs[0]
    assert r["rank"] == 3 and r["state"] == "running"
    assert r["bytes_planned"] == 200 and r["bytes_written"] == 50
    assert r["percent"] == 25.0
    assert kv.try_get("tpusnap_progress/t0/3") is not None
    # finish(committed) forces 100% and a terminal state.
    mon.finish("committed")
    r = read_progress_records(str(tmp_path))[0]
    assert r["state"] == "committed" and r["percent"] == 100.0
    rec.finalize()


def test_heartbeat_aborted_cleans_own_kv_key(tmp_path):
    rec = TakeTelemetry(rank=1, enabled=True)
    kv = MemoryKVStore()
    mon = _monitor(rec, tmp_path, FakeClock(), rank=1, kv=kv)
    mon.tick()
    assert kv.try_get("tpusnap_progress/t0/1") is not None
    mon.finish("aborted")
    assert kv.try_get("tpusnap_progress/t0/1") is None
    assert read_progress_records(str(tmp_path))[0]["state"] == "aborted"
    rec.finalize()


# --------------------------------------------------------- stall watchdog


def test_watchdog_fires_once_per_episode(tmp_path, caplog):
    rec = TakeTelemetry(rank=0, enabled=True)
    clk = FakeClock()
    mon = _monitor(rec, tmp_path, clk, stall_deadline_s=5.0)
    token = rec.op_enter("storage_write")
    with caplog.at_level(logging.WARNING, logger="tpusnap.progress"):
        mon.tick()  # baseline signature
        clk.t += 6.0
        mon.tick()
        stalls = [r for r in caplog.records if hasattr(r, "tpusnap_stall")]
        assert len(stalls) == 1
        info = stalls[0].tpusnap_stall
        assert info["op"] == "storage_write"
        assert info["rank"] == 0
        assert info["stalled_s"] >= 5.0
        assert info["missing_ranks"] is None
        # Still stalled: NO second warning for the same episode.
        clk.t += 6.0
        mon.tick()
        assert (
            len([r for r in caplog.records if hasattr(r, "tpusnap_stall")])
            == 1
        )
        # Forward progress resets the episode; a NEW stall warns again.
        rec.record_span("x", 0.0, 0.01)
        mon.tick()
        clk.t += 6.0
        mon.tick()
        assert (
            len([r for r in caplog.records if hasattr(r, "tpusnap_stall")])
            == 2
        )
    rec.op_exit(token)
    rec.finalize()


def test_watchdog_requires_inflight_op(tmp_path, caplog):
    rec = TakeTelemetry(rank=0, enabled=True)
    clk = FakeClock()
    mon = _monitor(rec, tmp_path, clk, stall_deadline_s=5.0)
    with caplog.at_level(logging.WARNING, logger="tpusnap.progress"):
        mon.tick()
        clk.t += 60.0
        mon.tick()  # no op in flight: idle, not stalled
    assert not [r for r in caplog.records if hasattr(r, "tpusnap_stall")]
    rec.finalize()


def test_watchdog_names_missing_ranks(tmp_path, caplog):
    rec = TakeTelemetry(rank=0, enabled=True)
    clk = FakeClock()
    mon = _monitor(rec, tmp_path, clk, stall_deadline_s=5.0, world_size=4)
    mon.add_attribution(lambda: [2, 3])
    token = rec.op_enter("comm.barrier")
    with caplog.at_level(logging.WARNING, logger="tpusnap.progress"):
        mon.tick()
        clk.t += 6.0
        mon.tick()
    stalls = [r for r in caplog.records if hasattr(r, "tpusnap_stall")]
    assert len(stalls) == 1
    assert stalls[0].tpusnap_stall["missing_ranks"] == [2, 3]
    assert stalls[0].tpusnap_stall["op"] == "comm.barrier"
    assert "[2, 3]" in stalls[0].getMessage()
    rec.op_exit(token)
    rec.finalize()


# ------------------------------------------------------------- skew math


def test_rollup_phase_skew_and_max_rank():
    a = {
        "rank": 0,
        "take_wall_s": 1.0,
        "phase_coverage": 0.95,
        "phases": {"stage": 0.2, "io_drain": 0.1},
        "stages": {"storage_write": {"count": 1, "total_s": 0.1, "p50_s": 0.1, "max_s": 0.1}},
    }
    b = {
        "rank": 1,
        "take_wall_s": 2.0,
        "phase_coverage": 0.95,
        "phases": {"stage": 0.2, "io_drain": 0.9},
        "stages": {"storage_write": {"count": 1, "total_s": 0.8, "p50_s": 0.8, "max_s": 0.8}},
    }
    r = rollup_summaries([a, b])
    assert r["stages"]["storage_write"]["max_rank"] == 1
    skew = r["phase_skew"]["io_drain"]
    assert skew["max_rank"] == 1
    assert skew["max_s"] == pytest.approx(0.9)
    assert skew["skew"] == pytest.approx(0.9 / 0.9)  # p50 of [0.1, 0.9] -> 0.9
    assert r["phase_skew"]["stage"]["skew"] == pytest.approx(1.0)


# ---------------------------------------------------------- path helpers


def test_local_root_of():
    assert local_root_of("/tmp/x/snap") == "/tmp/x/snap"
    assert local_root_of("file:///tmp/x") == "/tmp/x"
    assert local_root_of("chaos+fs:///tmp/x") == "/tmp/x"
    assert local_root_of("s3://bucket/key") is None
    assert local_root_of("chaos+s3://bucket/key") is None


def test_restore_trace_dir_spelling_invariant():
    """Every spelling of the same local destination digests to the same
    trace dir — a restore via 'file://...' must be findable by
    `trace --restore /plain/path` (and vice versa)."""
    plain = restore_trace_dir("/tmp/x/snap")
    assert restore_trace_dir("file:///tmp/x/snap") == plain
    assert restore_trace_dir("chaos+fs:///tmp/x/snap") == plain
    assert restore_trace_dir("/tmp/x/snap/") == plain
    assert restore_trace_dir("s3://b/snap") != plain


# ------------------------------------------------------- stall fault kind


def test_stall_fault_spec_parse():
    assert FaultPlan.from_spec("stall_op=write:2:1.5").stall_op == ("write", 2, 1.5)
    assert FaultPlan.from_spec("stall_op=read:*:0.5").stall_op == ("read", 0, 0.5)
    with pytest.raises(ValueError):
        FaultPlan.from_spec("stall_nope=1")


def test_stall_fault_injects_in_op_sleep(tmp_path):
    telemetry.reset_global_counters()
    path = str(tmp_path / "snap")
    t0 = time.perf_counter()
    snap = Snapshot.take(
        "chaos+fs://" + path,
        {"m": PytreeState({"w": np.ones(2048, np.float32)})},
        storage_options={"fault_plan": FaultPlan(stall_op=("write", 1, 0.15))},
    )
    assert time.perf_counter() - t0 >= 0.15
    assert telemetry.counter_value("faults.stalled.write") == 1
    assert snap.verify().clean


# ------------------------------------------------ take heartbeat records


def test_take_heartbeat_reaches_100_at_commit(tmp_path):
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": PytreeState({"w": np.ones(4096, np.float32)})})
    recs = read_progress_records(path)
    assert len(recs) == 1
    assert recs[0]["state"] == "committed"
    assert recs[0]["percent"] == 100.0
    assert recs[0]["phase"] is not None


def test_telemetry_off_skips_heartbeats_entirely(tmp_path):
    path = str(tmp_path / "snap")
    with override_telemetry_enabled(False):
        Snapshot.take(path, {"m": PytreeState({"w": np.ones(1024, np.float32)})})
    assert not os.path.exists(os.path.join(path, PROGRESS_DIR))


def test_aborted_take_publishes_aborted_record(tmp_path):
    path = str(tmp_path / "snap")

    class Boom(RuntimeError):
        pass

    class BadState:
        def state_dict(self):
            return {"w": np.ones(256, np.float32)}

        def load_state_dict(self, sd):
            pass

    # Fail inside the write pipeline (journal off so the first faulted
    # op is a blob write, after the monitor has started): transients
    # that never converge exhaust the shortened retry deadline.
    from tpusnap.knobs import override_journal_disabled

    with override_journal_disabled(True), pytest.raises(Exception):
        Snapshot.take(
            "chaos+fs://" + path,
            {"m": BadState()},
            storage_options={
                "fault_plan": FaultPlan(transient_per_op=10**6),
                "retry_deadline_sec": 0.3,
                "retry_backoff_base_sec": 0.01,
            },
        )
    recs = read_progress_records(path)
    assert recs and recs[0]["state"] == "aborted"
    # The aborted breadcrumb is observability-only: the path still
    # classifies empty (reusable), not foreign.
    from tpusnap import fsck_snapshot

    assert fsck_snapshot(path).state == "empty"


# ------------------------------------------------------------- watch CLI


def test_watch_once_no_records_exits_3(tmp_path, capsys):
    from tpusnap.__main__ import main

    assert main(["watch", str(tmp_path), "--once"]) == 3
    out = capsys.readouterr().out
    assert "no heartbeat records yet" in out


def test_watch_rejects_non_local_path(capsys):
    from tpusnap.__main__ import main

    assert main(["watch", "s3://bucket/snap", "--once"]) == 1


def test_render_watch_table_flags_stalled():
    now = 1000.0
    records = [
        {"rank": 0, "state": "running", "phase": "stage", "op": "storage_write",
         "percent": 40.0, "mbps": 10.0, "beat_age_s": 0.1, "ts": now},
        {"rank": 1, "state": "running", "phase": "stage", "op": "comm.barrier",
         "percent": 5.0, "mbps": 0.0, "beat_age_s": 42.0, "ts": now},
    ]
    frame = render_watch_table(records, committed=False, stall_flag_s=10.0, now=now)
    lines = frame.splitlines()
    assert "STALLED" not in lines[1]
    assert "STALLED" in lines[2]
    assert "not yet written" in frame


def test_watch_live_take_shows_progress_to_100(tmp_path, capsys):
    """Acceptance: `tpusnap watch` against an in-flight (slowed) take in
    a subprocess shows running per-rank progress, then 100% at commit."""
    from tpusnap.__main__ import main

    snap = str(tmp_path / "snap")
    script = (
        "import numpy as np\n"
        "from tpusnap import Snapshot, PytreeState, FaultPlan\n"
        "state = {'w%d' % i: np.ones(1 << 14, dtype=np.float32) for i in range(8)}\n"
        f"Snapshot.take('chaos+fs://{snap}', {{'m': PytreeState(state)}},\n"
        "              storage_options={'fault_plan': FaultPlan(stall_op=('write', 6, 2.5))})\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.update(
        {
            "PYTHONPATH": _REPO_ROOT,
            "JAX_PLATFORMS": "cpu",
            "TPUSNAP_HEARTBEAT_INTERVAL_S": "0.05",
            "TPUSNAP_DISABLE_BATCHING": "1",
        }
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    frames = []
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            rc = main(["watch", snap, "--json"])
            out = capsys.readouterr().out.strip()
            if rc == 0 and out:
                frame = json.loads(out.splitlines()[-1])
                if frame["records"]:
                    frames.append(frame)
                    if frame["records"][0]["state"] != "running":
                        break
            time.sleep(0.1)
    finally:
        out, _ = proc.communicate(timeout=60)
    assert proc.returncode == 0, out
    running = [
        f["records"][0] for f in frames if f["records"][0]["state"] == "running"
    ]
    assert running, "watch never observed the take in flight"
    assert any(r["percent"] is not None for r in running)
    final = frames[-1]["records"][0]
    assert final["state"] == "committed"
    assert final["percent"] == 100.0
    assert final["phase"] is not None


# --------------------------------------------------------- restore traces


def test_restore_persists_trace_and_cli(tmp_path, capsys):
    from tpusnap.__main__ import main

    path = str(tmp_path / "snap")
    state = {"w%d" % i: np.arange(4096, dtype=np.float32) + i for i in range(4)}
    Snapshot.take(path, {"m": PytreeState(state)})
    with override_telemetry_dir(str(tmp_path / "teledir")):
        target = {
            "w%d" % i: np.zeros(4096, dtype=np.float32) for i in range(4)
        }
        Snapshot(path).restore({"m": PytreeState(target)})
        assert np.array_equal(target["w2"], state["w2"])
        # Acceptance: a rank trace readable by `trace --restore`, with
        # phase spans covering >= 90% of restore wall-clock.
        tf = os.path.join(restore_trace_dir(path), "rank_0.json")
        assert os.path.exists(tf)
        doc = json.load(open(tf))
        assert doc["kind"] == "restore"
        assert doc["summary"]["phase_coverage"] >= 0.9
        for phase in ("restore.plan", "restore.read", "restore.load"):
            assert phase in doc["summary"]["phases"], phase
        assert doc["summary"]["counters"]["storage.bytes_read"] > 0
        assert main(["trace", path, "--restore"]) == 0
        out = capsys.readouterr().out
        assert "restore.read" in out and "phase coverage" in out
        assert main(["trace", path, "--restore", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["kind"] == "restore"
        assert doc["rollup"]["phase_coverage_min"] >= 0.9


def test_back_to_back_restores_keep_run_scoped_traces(tmp_path):
    """Back-to-back restores of the same snapshot must NOT clobber each
    other's traces: each run writes its own rank_<k>.<run>.json, the
    rank_<k>.json latest-pointer tracks the newest, and retention is
    bounded per digest+rank."""
    from tpusnap.progress import RESTORE_TRACE_KEEP, load_restore_traces

    path = str(tmp_path / "snap")
    state = {"w": np.arange(4096, dtype=np.float32)}
    Snapshot.take(path, {"m": PytreeState(state)})
    with override_telemetry_dir(str(tmp_path / "teledir")):
        n_runs = RESTORE_TRACE_KEEP + 2
        for _ in range(n_runs):
            Snapshot(path).restore(
                {"m": PytreeState({"w": np.zeros(4096, np.float32)})}
            )
        tdir = restore_trace_dir(path)
        runs = [
            n
            for n in os.listdir(tdir)
            if n.startswith("rank_0.") and n != "rank_0.json"
        ]
        # Every run got its own file, bounded by the retention cap.
        assert len(runs) == RESTORE_TRACE_KEEP, sorted(runs)
        # The latest pointer resolves to one of the retained run files
        # and still reads as a full trace doc (what `trace --restore`
        # and `analyze --restore` load).
        latest = os.path.join(tdir, "rank_0.json")
        assert os.path.islink(latest)
        assert os.readlink(latest) in runs
        docs = load_restore_traces(path)
        assert sorted(docs) == [0]
        assert docs[0]["kind"] == "restore"
        assert docs[0]["run_id"] in os.readlink(latest)
        # Retained run files are distinct documents, not copies.
        run_ids = set()
        for n in runs:
            run_ids.add(json.load(open(os.path.join(tdir, n)))["run_id"])
        assert len(run_ids) == len(runs)


def test_trace_restore_falls_back_to_run_files(tmp_path, capsys):
    """A missing or dangling latest-pointer must not hide a rank's
    traces: ``load_restore_traces`` falls back to the newest run-scoped
    ``rank_<k>.<run>.json`` (a reaped tmpdir target, a partially-synced
    telemetry dir)."""
    from tpusnap.__main__ import main
    from tpusnap.progress import load_restore_traces, restore_trace_dir

    path = str(tmp_path / "snap")
    state = {"w": np.arange(4096, dtype=np.float32)}
    Snapshot.take(path, {"m": PytreeState(state)})
    with override_telemetry_dir(str(tmp_path / "teledir")):
        for _ in range(2):
            Snapshot(path).restore(
                {"m": PytreeState({"w": np.zeros(4096, np.float32)})}
            )
        tdir = restore_trace_dir(path)
        latest = os.path.join(tdir, "rank_0.json")
        want_run = load_restore_traces(path)[0]["run_id"]
        # Dangling symlink: target gone, pointer still there.
        os.remove(latest)
        os.symlink("rank_0.feedfeedfeed.json", latest)
        docs = load_restore_traces(path)
        assert docs and docs[0]["kind"] == "restore"
        assert docs[0]["run_id"] == want_run  # newest run file wins
        # Pointer absent entirely.
        os.remove(latest)
        docs = load_restore_traces(path)
        assert docs and docs[0]["run_id"] == want_run
        assert main(["trace", path, "--restore"]) == 0
        assert "restore.read" in capsys.readouterr().out


def test_trace_restore_without_traces_exits_3(tmp_path, capsys):
    from tpusnap.__main__ import main

    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": PytreeState({"w": np.ones(256, np.float32)})})
    with override_telemetry_dir(str(tmp_path / "empty_teledir")):
        assert main(["trace", path, "--restore"]) == 3
        assert "no restore telemetry" in capsys.readouterr().err


def test_restore_telemetry_off_skips_trace(tmp_path):
    path = str(tmp_path / "snap")
    state = {"w": np.ones(1024, np.float32)}
    Snapshot.take(path, {"m": PytreeState(state)})
    with override_telemetry_dir(str(tmp_path / "teledir")):
        with override_telemetry_enabled(False):
            Snapshot(path).restore(
                {"m": PytreeState({"w": np.zeros(1024, np.float32)})}
            )
        assert not os.path.exists(restore_trace_dir(path))


def test_async_restore_also_traces(tmp_path):
    path = str(tmp_path / "snap")
    state = {"w": np.arange(2048, dtype=np.float32)}
    Snapshot.take(path, {"m": PytreeState(state)})
    with override_telemetry_dir(str(tmp_path / "teledir")):
        target = {"w": np.zeros(2048, np.float32)}
        Snapshot(path).async_restore({"m": PytreeState(target)}).wait()
        assert np.array_equal(target["w"], state["w"])
        doc = json.load(
            open(os.path.join(restore_trace_dir(path), "rank_0.json"))
        )
        assert doc["summary"]["phase_coverage"] >= 0.9


# ------------------------------------------------------------ distributed


def _world_stall_take(snap_dir):
    import logging

    import numpy as np

    from tpusnap import FaultPlan, PytreeState, Snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logging.getLogger("tpusnap.progress").addHandler(Capture())
    # Rank 1's first blob write hangs for 6 s; rank 0 sails through and
    # blocks in the commit barrier. Its watchdog (deadline 1 s via
    # extra_env) must name the barrier and the exact missing rank well
    # before the 600 s barrier timeout.
    plan = (
        FaultPlan(stall_op=("write", 1, 6.0))
        if comm.rank == 1
        else FaultPlan()
    )
    state = {"w": np.arange(8192, dtype=np.float32) * (comm.rank + 1)}
    Snapshot.take(
        "chaos+fs://" + snap_dir,
        {"m": PytreeState(state)},
        storage_options={"fault_plan": plan},
    )
    if comm.rank == 0:
        stalls = [r for r in records if hasattr(r, "tpusnap_stall")]
        assert stalls, "healthy rank's watchdog never fired"
        barrier_stalls = [
            r.tpusnap_stall
            for r in stalls
            if r.tpusnap_stall.get("missing_ranks")
        ]
        assert barrier_stalls, [r.tpusnap_stall for r in stalls]
        info = barrier_stalls[0]
        assert info["missing_ranks"] == [1], info
        assert "barrier" in info["op"], info
        assert info["stalled_s"] < 60.0, info  # seconds, not the 600s timeout
        print("STALL_ATTRIBUTION_OK")


@pytest.mark.distributed
def test_two_proc_stall_watchdog_names_missing_rank(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    outs = run_subprocess_world(
        _world_stall_take,
        world_size=2,
        args=[str(tmp_path / "snap")],
        extra_env={
            "TPUSNAP_STALL_DEADLINE_S": "1.0",
            "TPUSNAP_HEARTBEAT_INTERVAL_S": "0.1",
        },
    )
    assert any("STALL_ATTRIBUTION_OK" in o for o in outs)
