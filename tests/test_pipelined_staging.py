"""Pipelined chunk-grain async staging (fast tier-1 suite, marker
``pipelined``).

The contract under test: an ``async_take`` of a state larger than
TPUSNAP_ASYNC_STAGE_WINDOW_BYTES returns control after staging ONE
window — blocked time and resident clone bytes are O(window), the
residual windows clone on the background drain interleaved with their
storage I/O, and the committed snapshot is bit-exact regardless. Plus
the opt-in COW mode (hash-verify-at-write instead of cloning) and the
``async_blocked_s`` history/regression wiring.
"""

import asyncio
import glob
import os
import time

import numpy as np
import pytest

from tpusnap import PytreeState, Snapshot, StateDict
from tpusnap import telemetry as tele_mod
from tpusnap.io_types import BufferStager, WriteReq
from tpusnap.knobs import (
    override_async_cow,
    override_async_stage_window_bytes,
    override_batching_disabled,
    override_journal_disabled,
    override_memory_budget_bytes,
    override_stage_threads,
)
from tpusnap.scheduler import execute_write_reqs
from tpusnap.storage_plugins.fs import FSStoragePlugin

pytestmark = pytest.mark.pipelined

_N = 8
_PER = 1 << 18  # 256 KiB per array; async staging cost is 2x


def _state(n=_N, per=_PER, seed=7):
    return {
        f"w{i}": np.random.default_rng(seed * 100 + i)
        .integers(0, 255, per, dtype=np.uint8)
        .view(np.float32)
        for i in range(n)
    }


def _blob_files(root):
    return [
        f
        for f in glob.glob(os.path.join(root, "**", "*"), recursive=True)
        if os.path.isfile(f)
        and ".tpusnap" not in f.split(os.sep)
        and not f.endswith(".snapshot_metadata")
    ]


def _restore_and_check(path, state):
    tgt = {"m": PytreeState({k: np.zeros_like(v) for k, v in state.items()})}
    Snapshot(path).restore(tgt)
    for k, v in state.items():
        assert np.array_equal(tgt["m"].tree[k].view(np.uint8), v.view(np.uint8)), k


# ------------------------------------------------------ scheduler-level


class _UnitStager(BufferStager):
    live = 0
    peak = 0

    def __init__(self, data):
        self.data = data

    async def stage_buffer(self, executor=None):
        _UnitStager.live += 1
        _UnitStager.peak = max(_UnitStager.peak, _UnitStager.live)
        await asyncio.sleep(0.002)
        return self.data

    def get_staging_cost_bytes(self) -> int:
        return len(self.data)


def test_pipelined_execute_returns_at_first_window(tmp_path):
    """The engine hands back a resumable PendingIOWork once one window's
    worth of staging cost is staged; complete() stages the rest under
    the window bound and writes everything."""
    _UnitStager.live = 0
    _UnitStager.peak = 0
    unit = 1000

    class DecPlugin(FSStoragePlugin):
        async def write(self, write_io) -> None:
            await asyncio.sleep(0.005)
            await super().write(write_io)
            _UnitStager.live -= 1

    plugin = DecPlugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=f"b{i}", buffer_stager=_UnitStager(os.urandom(unit)))
        for i in range(10)
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs,
            plugin,
            memory_budget_bytes=1 << 30,
            rank=0,
            pipelined_staging=True,
        )
        # Window = 2 units: staging must NOT have completed at return.
        assert not pending.staging_complete()
        staged_at_return = _UnitStager.peak
        assert staged_at_return <= 3  # window (2) + the >=1 admission
        await pending.complete()
        assert pending.staging_complete()

    with override_async_stage_window_bytes(2 * unit):
        asyncio.run(go())
    for i in range(10):
        assert (tmp_path / f"b{i}").exists()
    # Resident staged-but-unwritten buffers stayed window-bounded
    # through the drain too.
    assert _UnitStager.peak <= 3, f"window unenforced: peak {_UnitStager.peak}"


def test_pipelined_stage_eagerly_requests_stage_in_blocked_window(tmp_path):
    """Requests selected by stage_eagerly (stage-time manifest
    annotators on multi-process takes) stage before control returns,
    even past the window target."""
    staged = []

    class S(BufferStager):
        def __init__(self, name, data):
            self.name = name
            self.data = data

        async def stage_buffer(self, executor=None):
            staged.append(self.name)
            return self.data

        def get_staging_cost_bytes(self) -> int:
            return len(self.data)

    plugin = FSStoragePlugin(root=str(tmp_path))
    write_reqs = [
        WriteReq(path=f"e{i}", buffer_stager=S(f"e{i}", os.urandom(500)))
        for i in range(4)
    ] + [
        WriteReq(path=f"d{i}", buffer_stager=S(f"d{i}", os.urandom(500)))
        for i in range(4)
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs,
            plugin,
            memory_budget_bytes=1 << 30,
            rank=0,
            pipelined_staging=True,
            stage_eagerly=lambda wr: wr.path.startswith("e"),
        )
        at_return = list(staged)
        assert {f"e{i}" for i in range(4)} <= set(at_return), at_return
        await pending.complete()

    with override_async_stage_window_bytes(1000):
        asyncio.run(go())


def test_stage_eagerly_holds_window_open_across_threads(tmp_path):
    """Completed NON-eager stagers must not count against the eager
    set: with TPUSNAP_STAGE_THREADS=2, fast non-eager stagers that
    overshoot the window target while a slow eager stager is still in
    flight may not close the blocked window early."""
    staged = []

    class S(BufferStager):
        def __init__(self, name, data, delay):
            self.name = name
            self.data = data
            self.delay = delay

        async def stage_buffer(self, executor=None):
            await asyncio.sleep(self.delay)
            staged.append(self.name)
            return self.data

        def get_staging_cost_bytes(self) -> int:
            return len(self.data)

    plugin = FSStoragePlugin(root=str(tmp_path))
    # One slow eager annotator + fast non-eager bulk whose cost alone
    # exceeds the window target.
    write_reqs = [
        WriteReq(path="eager", buffer_stager=S("eager", os.urandom(400), 0.15))
    ] + [
        WriteReq(path=f"d{i}", buffer_stager=S(f"d{i}", os.urandom(600), 0.001))
        for i in range(6)
    ]

    async def go():
        pending = await execute_write_reqs(
            write_reqs,
            plugin,
            memory_budget_bytes=1 << 30,
            rank=0,
            pipelined_staging=True,
            stage_eagerly=lambda wr: wr.path == "eager",
        )
        assert "eager" in staged, f"window closed mid-eager: {staged}"
        await pending.complete()

    with override_stage_threads(2), override_async_stage_window_bytes(1200):
        asyncio.run(go())


# ------------------------------------------------------- take-level (a)


def test_blocked_window_is_budget_bounded(tmp_path):
    """Satellite (a): an async take of N windows under a tight memory
    budget keeps peak staged bytes <= budget (budget high-water gauge)
    and returns control BEFORE all blobs exist on disk; the commit then
    completes and restores bit-exact."""
    state = _state()
    budget = 2 * 2 * _PER  # two in-flight clones (async cost is 2x)
    path = str(tmp_path / "snap")
    with override_batching_disabled(True), override_journal_disabled(
        True
    ), override_memory_budget_bytes(budget):
        pending = Snapshot.async_take(
            "chaos+fs://" + path,
            {"m": PytreeState(state)},
            # Every write stalls 0.6 s inside the op: nothing can land
            # on disk within the blocked window's return path.
            storage_options={"fault_plan": {"stall_op": ("write", 0, 0.6)}},
        )
        # Control is back before the drain produced all blobs (or any
        # metadata): the pipelined window is doing its job.
        assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))
        assert len(_blob_files(path)) < _N
        snap = pending.wait()
        assert pending.staged()
    summary = tele_mod.LAST_TAKE_SUMMARY
    high_water = summary["gauges"]["scheduler.budget_used_bytes"]
    assert high_water <= budget, (high_water, budget)
    assert summary["counters"]["scheduler.bytes_staged"] == _N * _PER
    assert os.path.exists(os.path.join(path, ".snapshot_metadata"))
    _restore_and_check(snap.path, state)


def test_window_fits_state_keeps_strict_semantics(tmp_path):
    """States at or under the window stage COMPLETELY inside the
    blocked window — the pre-pipeline consistency contract (mutate
    in place right after return) holds exactly, as does window=0."""
    state = _state(n=3)
    # override_async_cow(False): "mutate right after return" is the
    # defensive-CLONE contract; the default COW mode's contract is
    # wait_staged() (covered in the COW section below).
    for window in (1 << 30, 0):
        path = str(tmp_path / f"snap{window}")
        with override_async_stage_window_bytes(window), override_async_cow(
            False
        ):
            pending = Snapshot.async_take(path, {"m": PytreeState(state)})
            assert pending.staged()  # frozen before control returned
            # "Training step": in-place mutation while I/O drains.
            mutated = {k: v.copy() for k, v in state.items()}
            for v in state.values():
                v.view(np.uint8)[:] = 0xAB
            pending.wait()
            _restore_and_check(path, mutated)
            for k, v in mutated.items():  # restore sources for next loop
                state[k][:] = v


def test_stall_in_drain_does_not_extend_blocked_window(tmp_path):
    """Satellite (c): a chaos ``stall`` fault on every storage write
    (the background drain's leg) must not extend the blocked window —
    writes are gated out of it entirely."""
    state = _state()
    stall_s = 1.2
    path = str(tmp_path / "snap")
    with override_batching_disabled(True), override_journal_disabled(
        True
    ), override_async_stage_window_bytes(2 * 2 * _PER):
        t0 = time.perf_counter()
        pending = Snapshot.async_take(
            "chaos+fs://" + path,
            {"m": PytreeState(state)},
            storage_options={
                "fault_plan": {"stall_op": ("write", 0, stall_s)}
            },
        )
        blocked = time.perf_counter() - t0
        pending.wait()
    assert blocked < stall_s, (
        f"blocked window {blocked:.2f}s swallowed the drain's "
        f"{stall_s}s write stall"
    )
    summary = tele_mod.LAST_TAKE_SUMMARY
    assert summary["async_blocked_s"] < stall_s
    _restore_and_check(path, state)


def test_single_stage_thread_by_default(tmp_path, monkeypatch):
    """Satellite: the clone executor is sized by TPUSNAP_STAGE_THREADS
    (default 1 — interleaved clone threads measured slower than one),
    not hardcoded."""
    from tpusnap.knobs import get_stage_threads
    from tpusnap.scheduler import _WriteScheduler

    # The ambient environment may legitimately set the knob (TPU-VM
    # operators are told to); the DEFAULT is what's under test.
    monkeypatch.delenv("TPUSNAP_STAGE_THREADS", raising=False)
    assert get_stage_threads() == 1
    with override_stage_threads(3):
        sched = _WriteScheduler(
            [], FSStoragePlugin(root=str(tmp_path)), 1 << 20, rank=0
        )
        try:
            assert sched.stage_concurrency == 3
            assert sched.executor._max_workers == 3
        finally:
            sched.executor.shutdown(wait=False)
            sched.hash_executor.shutdown(wait=False)


def test_warm_pool_reuse_across_windows(tmp_path):
    """Steady-state windows allocate nothing: window N+1's clones reuse
    the buffers window N's writes released (pool high-water stays at
    about one window, not the state size)."""
    import tpusnap._staging_pool as sp

    sp.clear()
    state = _state()
    path = str(tmp_path / "snap")
    # Clone mode: the pool LIFO contract under test only exists when
    # staging clones (the default COW mode clones nothing).
    with override_batching_disabled(True), override_journal_disabled(
        True
    ), override_async_stage_window_bytes(2 * 2 * _PER), override_async_cow(
        False
    ):
        Snapshot.async_take(path, {"m": PytreeState(state)}).wait()
    try:
        # All clones parked back; far fewer distinct buffers than blobs.
        assert 0 < sp.free_bytes() < _N * _PER, sp.free_bytes()
    finally:
        sp.clear()
    _restore_and_check(path, state)


# ------------------------------------------------------------ COW mode


def test_cow_frozen_state_clones_nothing(tmp_path):
    """TPUSNAP_ASYNC_COW: unmutated (frozen) arrays are written straight
    from live memory — the staging pool sees zero clone traffic — and
    the hash-verify-at-write pass accepts them."""
    import tpusnap._staging_pool as sp

    sp.clear()
    state = _state()
    path = str(tmp_path / "snap")
    with override_batching_disabled(True), override_async_cow(True):
        pending = Snapshot.async_take(path, {"m": PytreeState(state)})
        snap = pending.wait()
    assert sp.free_bytes() == 0  # no clone buffers were ever acquired
    summary = tele_mod.LAST_TAKE_SUMMARY
    assert summary["stages"].get("cow_verify", {}).get("count") == _N
    _restore_and_check(snap.path, state)


def test_cow_detects_concurrent_mutation(tmp_path):
    """TPUSNAP_ASYNC_COW: mutating an array between staging (hash
    recorded) and its storage write fails the take loudly — the
    metadata is never committed, torn bytes are never silently blessed."""
    state = _state(n=4)
    path = str(tmp_path / "snap")
    with override_batching_disabled(True), override_journal_disabled(
        True
    ), override_async_cow(True), override_async_stage_window_bytes(
        2 * _PER
    ):
        pending = Snapshot.async_take(
            "chaos+fs://" + path,
            {"m": PytreeState(state)},
            # Every write stalls 1 s: the mutation below lands before
            # any write reads the live bytes.
            storage_options={"fault_plan": {"stall_op": ("write", 0, 1.0)}},
        )
        # COW-aware rendezvous: staging per-se is done (no clones) but
        # the live bytes stay aliased until the stalled writes drain —
        # staged()/wait_staged() must NOT report safe-to-mutate yet.
        assert not pending.wait_staged(timeout=0.05)
        assert not pending.staged()
        for v in state.values():
            v.view(np.uint8)[:] = 0x5A
        with pytest.raises(RuntimeError, match="concurrent mutation"):
            pending.wait()
    assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))


def test_cow_verify_checks_xxh64_lane():
    """verify_cow_after_write re-verifies the 64-bit dedup lane when
    recorded — a mutation that (hypothetically) collides the 32-bit
    CRC lane is still caught."""
    from tpusnap import _native
    from tpusnap.io_preparers.array import ArrayBufferStager, _record_checksums
    from tpusnap.manifest import TensorEntry

    data = np.arange(1024, dtype=np.uint8)
    entry = TensorEntry(
        location="w", serializer="buffer_protocol", dtype="uint8",
        shape=[1024], replicated=False, byte_range=None,
    )
    _record_checksums(entry, memoryview(data.tobytes()), True)
    assert entry.dedup_hash or entry.tile_dedup_hashes
    stager = ArrayBufferStager(data, is_async_snapshot=True, entry=entry)
    stager.verify_cow_after_write(data.tobytes())  # unmutated: clean
    mutated = bytearray(data.tobytes())
    mutated[0] ^= 0xFF
    with pytest.raises(_native.ChecksumError):
        # Bypass the CRC lane: the xxh lane alone must catch it.
        stager._verify_cow_xxh_lane(memoryview(bytes(mutated)))


def test_cow_slab_members_verified_against_slab_copy(tmp_path, monkeypatch):
    """COW + batching: slab members return LIVE bytes and the slab copy
    is their effective clone — the fill pass must verify the copy
    against the stage-time hash (the write pipeline only sees the slab
    stager's cow_pending), so a mutation between the member's hash pass
    and the slab copy fails the take loudly."""
    from tpusnap.io_preparers.array import ArrayBufferStager

    # Happy path: small arrays pack into a slab, COW members verify
    # clean against their slab copy, take commits and restores.
    state = _state(n=4)
    path = str(tmp_path / "ok")
    with override_async_cow(True):
        snap = Snapshot.async_take(path, {"m": PytreeState(state)}).wait()
    _restore_and_check(snap.path, state)

    # Mutation between the member's hash pass and the slab copy: wrap
    # stage_buffer to mutate the live array right after the hash is
    # recorded (deterministic — no timing race).
    orig = ArrayBufferStager.stage_buffer

    async def mutate_after_hash(self, executor=None):
        buf = await orig(self, executor)
        if getattr(self, "cow_pending", False):
            np.asarray(self.arr).view(np.uint8)[:1] ^= 0xFF
        return buf

    monkeypatch.setattr(ArrayBufferStager, "stage_buffer", mutate_after_hash)
    bad = str(tmp_path / "bad")
    with override_async_cow(True), override_journal_disabled(True):
        with pytest.raises(RuntimeError, match="concurrent mutation"):
            Snapshot.async_take(bad, {"m": PytreeState(_state(n=4))}).wait()
    assert not os.path.exists(os.path.join(bad, ".snapshot_metadata"))


# -------------------------------------------------- history/regression


def test_async_blocked_s_recorded_and_gated(tmp_path):
    """Satellite: async_blocked_s lands in the take summary and the
    history event, and `history --check` grades it as a duration
    (upward regressions fire)."""
    from tpusnap import check_regression
    from tpusnap import history as hist
    from tpusnap.knobs import override_telemetry_dir

    state = _state(n=2)
    with override_telemetry_dir(str(tmp_path / "tele")):
        hist._reset_process_state()
        Snapshot.async_take(str(tmp_path / "s"), {"m": PytreeState(state)}).wait()
        events = hist.load_history()
        takes = [e for e in events if e.get("kind") == "take"]
        assert takes and isinstance(takes[-1].get("async_blocked_s"), float)

        # Synthetic trend: a 2x slower blocked window must regress.
        base = dict(takes[-1], cold=False)
        evs = []
        for i in range(5):
            evs.append(dict(base, async_blocked_s=0.1, ts=i))
        evs.append(dict(base, async_blocked_s=0.25, ts=9))
        report = check_regression(
            evs, kind="take", metric="async_blocked_s", min_baseline=3
        )
        assert report.ok and report.regressed, report.reason
        ok = check_regression(
            evs[:-1], kind="take", metric="async_blocked_s", min_baseline=3
        )
        assert ok.ok and not ok.regressed, ok.reason
