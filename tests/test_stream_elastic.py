"""Elastic multi-process delta streams (ISSUE 16).

Crash matrix for ``Snapshot.stream`` with ``world_size > 1``:

- a rank SIGKILLed mid-micro-commit must NOT kill the stream —
  fully-replicated epochs commit DEGRADED and streaming continues on
  the survivors (the acceptance scenario);
- sharded state cannot be adopted, so the same death tears the epoch
  and PAUSES the stream (named, policy-handled — never a wedge); a
  fresh world reopening the root RESUMES the committed chain and the
  retake salvages the torn member's journal-proven bytes;
- a graceful ``leave()`` plus a later re-join re-plan the world at the
  next capture boundary, with the joins/leaves recorded per epoch in
  ``extras["delta"]["world"]``.

Plus unit coverage for the satellites that ride along: the ``preempt``
fault kind, the terminal ``left`` lease state, the ``slo --check``
stream-cadence gate, and the fsck/info chain-report world rendering.
"""

import os
import re
import signal
import time

import numpy as np
import pytest

# Mirrors tests/test_liveness.py: tight leases so detection fits the
# test budget, batching off so retake layouts match for salvage.
_TTL = 2.0
_ENV = {
    "TPUSNAP_LIVENESS_TTL_S": "2.0",
    "TPUSNAP_HEARTBEAT_INTERVAL_S": "0.1",
    "TPUSNAP_DISABLE_BATCHING": "1",
    "TPUSNAP_HISTORY": "0",
    "TPUSNAP_RANK_FAILURE": "degrade",
}


def _state(nbytes_per_arr=1 << 16, n=4, seed=7):
    rng = np.random.default_rng(seed)
    return {
        f"w{i}": rng.standard_normal(nbytes_per_arr // 8).astype(np.float64)
        for i in range(n)
    }


def _arm_kill_on_next_write(armed):
    """Rank-local: SIGKILL this process on the first storage write
    (blob payloads only, not lifecycle sidecars) after ``armed[0]``
    flips — the deterministic 'die mid-micro-commit' window."""
    import tpusnap.storage_plugins.fs as fs_mod

    orig_write = fs_mod.FSStoragePlugin.write

    async def hooked_write(self, write_io):
        await orig_write(self, write_io)
        if armed[0] and not write_io.path.startswith(".tpusnap"):
            os.kill(os.getpid(), signal.SIGKILL)

    fs_mod.FSStoragePlugin.write = hooked_write


def _wait(pred, deadline_s, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        if pred():
            return time.monotonic() - t0
        time.sleep(0.05)
    raise AssertionError(f"timed out after {deadline_s}s waiting for {what}")


# --------------------------------------------------------------------------
# (a) Replicated stream survives SIGKILL of a rank: degraded epoch,
#     then solo epochs — the ISSUE 16 acceptance scenario.
# --------------------------------------------------------------------------


def _world_stream_survives_sigkill(root):
    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator
    from tpusnap.delta import resolve_chain

    comm = get_communicator()
    arrays = _state(seed=11)
    state = {"m": StateDict(step=7, **arrays)}

    armed = [False]
    if comm.rank == 1:
        _arm_kill_on_next_write(armed)

    stream = Snapshot.stream(root, state, cadence_s=0.5, replicated=["**"])
    # Base + one clean multi-rank epoch first, so the kill lands inside
    # a DELTA micro-commit (the same gate arms both ranks' clocks).
    _wait(lambda: stream.stats["commits"] >= 2, 45, "base + first epoch")
    # Mutate in place (identically on both ranks — replicated state)
    # right after a commit landed: the next epoch has REAL blob writes
    # for the kill hook to land in, and the mutation is done long
    # before the next cadence boundary captures it.
    for v in arrays.values():
        v += 1.0
    t_armed = time.monotonic()
    armed[0] = True  # rank 1 dies on its next blob write

    if comm.rank == 1:
        time.sleep(120)
        os._exit(3)  # the hooked write should have SIGKILLed us
    _wait(
        lambda: stream.stats["degraded_epochs"] >= 1,
        3 * _TTL + 30,
        "a degraded epoch",
    )
    dt = time.monotonic() - t_armed
    print(f"STREAM-DEGRADED dt={dt:.1f}", flush=True)
    # The stream is not paused and keeps committing WITHOUT rank 1.
    assert not stream.paused
    after = stream.stats["commits"]
    _wait(lambda: stream.stats["commits"] > after, 30, "a post-death epoch")
    assert stream.members == [0], stream.members
    stream.close(final_commit=False)

    rep = resolve_chain(root)
    assert rep.head and not rep.torn_tail, rep.summary()
    assert "DEGRADED" in rep.summary(), rep.summary()
    by_name = {m.name: m for m in rep.members}
    deg = [m for m in rep.members if m.degraded]
    assert deg and deg[0].degraded["dead_ranks"], rep.summary()
    # Per-epoch world forensics: the degraded epoch ran the full world;
    # the head (post-death) epoch re-planned down to the survivor.
    assert deg[0].world and deg[0].world["ranks"] == [0, 1], deg[0]
    head = by_name[rep.head]
    assert head.world and head.world["ranks"] == [0], head
    assert head.world.get("left") == [1] or head.world.get("expired") == [1]

    # Bit-exact restore from the survivor-committed chain.
    target = {
        "m": StateDict(
            step=0, **{k: np.zeros_like(v) for k, v in arrays.items()}
        )
    }
    Snapshot(rep.head_path).restore(target)
    assert target["m"]["step"] == 7
    for k, v in arrays.items():
        assert np.array_equal(target["m"][k], v), k
    from tpusnap import verify_snapshot

    vr = verify_snapshot(rep.head_path)
    assert vr.clean and not vr.corrupt, vr
    print("STREAM-SURVIVED-OK", flush=True)
    os._exit(0)  # skip the shutdown rendezvous with the dead peer


@pytest.mark.distributed
def test_stream_survives_rank_sigkill(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    root = str(tmp_path / "stream_sigkill")
    with pytest.raises(RuntimeError) as ei:
        run_subprocess_world(
            _world_stream_survives_sigkill,
            world_size=2,
            args=[root],
            extra_env=_ENV,
            timeout=150,
        )
    logs = str(ei.value)
    assert "STREAM-SURVIVED-OK" in logs, logs[-4000:]
    m = re.search(r"STREAM-DEGRADED dt=([0-9.]+)", logs)
    assert m, logs[-4000:]
    # Death -> degraded epoch within detection (<= 3x TTL) plus one
    # cadence + the adoption protocol (generous CI slack).
    assert float(m.group(1)) <= 3 * _TTL + 25


# --------------------------------------------------------------------------
# (b) Sharded stream: death tears the epoch and PAUSES the stream;
#     a fresh world reopening the root resumes + salvages.
# --------------------------------------------------------------------------


def _make_sharded(bump):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = np.array(jax.devices()).reshape(-1)
    mesh = Mesh(devices, ("x",))
    sharding = NamedSharding(mesh, PartitionSpec("x"))
    n = len(devices) * 8
    full = np.arange(n * 512, dtype=np.float32).reshape(n, 512) + bump
    return jax.make_array_from_callback(
        full.shape, sharding, lambda idx: full[idx]
    )


def _sharded_state(bump=0.0):
    from tpusnap import StateDict

    arrays = {k: v + bump for k, v in _state(n=2, seed=3).items()}
    return {"m": StateDict(s=_make_sharded(bump), **arrays)}


def _world_stream_sharded_pause(root):
    from tpusnap import Snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    state = _sharded_state()

    armed = [False]
    if comm.rank == 1:
        _arm_kill_on_next_write(armed)

    stream = Snapshot.stream(root, state, cadence_s=0.5, replicated=["m/w*"])
    _wait(lambda: stream.stats["commits"] >= 2, 60, "base + first epoch")
    # Swap in bump=1 state (identically on both ranks) so the next
    # epoch has real writes; the resume world reconstructs the SAME
    # bump=1 state, which is what makes the torn member's journaled
    # bytes salvageable on the retake.
    for k, v in _sharded_state(bump=1.0)["m"].items():
        state["m"][k] = v
    armed[0] = True

    if comm.rank == 1:
        time.sleep(120)
        os._exit(3)  # the hooked write should have SIGKILLed us
    _wait(lambda: stream.paused, 3 * _TTL + 30, "stream pause")
    info = stream.pause_info
    assert info and info["dead_ranks"] == [1], info
    assert info["member"], info
    # Paused is terminal-but-named: closed, not failed.
    assert stream.closed
    stream.raise_if_failed()  # a pause is NOT a worker failure
    print(f"STREAM-PAUSED-OK member={info['member']}", flush=True)
    os._exit(0)  # skip the shutdown rendezvous with the dead peer


def _world_stream_resume_salvages(root):
    from tpusnap import Snapshot, telemetry, verify_snapshot
    from tpusnap.comm import get_communicator
    from tpusnap.delta import resolve_chain

    comm = get_communicator()
    state = _sharded_state(bump=1.0)  # what the torn epoch captured
    before = resolve_chain(root)
    assert before.torn_tail, before.summary()
    committed_seq = max(
        m.seq for m in before.members if m.state == "committed"
    )

    salv0 = telemetry.counter_value("salvage.bytes_salvaged")
    stream = Snapshot.stream(root, state, cadence_s=0.5, replicated=["m/w*"])
    # RESUME, not a second base: the committed chain's identity and seq
    # carry over across process lifetimes.
    assert stream.seq == committed_seq, (stream.seq, committed_seq)
    _wait(lambda: stream.stats["commits"] >= 1, 60, "resumed micro-commit")
    salvaged = telemetry.counter_value("salvage.bytes_salvaged") - salv0
    stream.close(final_commit=False)

    rep = resolve_chain(root)
    assert rep.head and not rep.torn_tail, rep.summary()
    assert not os.path.isdir(os.path.join(root, "base-000001"))
    if comm.rank == 0:
        # The retake of the torn member reused the survivor's
        # journal-proven bytes instead of rewriting them.
        assert salvaged > 0, salvaged
        vr = verify_snapshot(rep.head_path)
        assert vr.clean and not vr.corrupt, vr
        print(f"STREAM-RESUMED-OK salvaged={salvaged}", flush=True)


@pytest.mark.distributed
def test_stream_sharded_death_pauses_then_resume_salvages(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    root = str(tmp_path / "stream_sharded")
    with pytest.raises(RuntimeError) as ei:
        run_subprocess_world(
            _world_stream_sharded_pause,
            world_size=2,
            args=[root],
            extra_env=_ENV,
            timeout=150,
        )
    logs = str(ei.value)
    assert "STREAM-PAUSED-OK" in logs, logs[-4000:]

    # The torn epoch kept its salvage substrate and named the world.
    from tpusnap.delta import resolve_chain

    rep = resolve_chain(root)
    assert rep.torn_tail, rep.summary()
    torn = next(m for m in rep.members if m.name == rep.torn_tail)
    assert torn.world and torn.world["ranks"] == [0, 1], torn

    # A FRESH world reopens the root: the stream resumes the committed
    # chain and the retake salvages the torn member.
    logs2 = run_subprocess_world(
        _world_stream_resume_salvages,
        world_size=2,
        args=[root],
        extra_env=_ENV,
        timeout=150,
    )
    assert any("STREAM-RESUMED-OK" in log for log in logs2), logs2


# --------------------------------------------------------------------------
# (c) Graceful leave + later re-join: the world re-plans at the next
#     capture boundary and the per-epoch record names both events.
# --------------------------------------------------------------------------


def _touch(path):
    with open(path, "w") as f:
        f.write("1")
        f.flush()
        os.fsync(f.fileno())


def _world_stream_leave_rejoin(root, sync_dir):
    from tpusnap import Snapshot, StateDict
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    arrays = _state(n=3, seed=5)
    state = {"m": StateDict(step=1, **arrays)}
    stream = Snapshot.stream(root, state, cadence_s=0.4, replicated=["**"])
    _wait(lambda: stream.stats["commits"] >= 2, 45, "base + first epoch")

    if comm.rank == 1:
        head = stream.leave()
        assert head is not None  # committed recovery point exists
        assert stream.closed and not stream.paused
        print("R1-LEFT", flush=True)
        # Re-join the still-live stream on the same root: a solo open
        # against the incumbents' registration, no collectives.
        st2 = Snapshot.stream(root, state, cadence_s=0.4, replicated=["**"])
        assert st2.stats["joins"] == 1
        _wait(
            lambda: st2.stats["commits"] >= 1 and 1 in st2.members,
            45,
            "re-joined epoch",
        )
        print("R1-REJOINED", flush=True)
        _wait(lambda: os.path.exists(os.path.join(sync_dir, "r0_done")), 45,
              "rank 0 ack")
        st2.leave()
    else:
        _wait(lambda: stream.members == [0], 45, "solo epoch after leave")
        print("R0-SAW-LEAVE", flush=True)
        _wait(lambda: stream.members == [0, 1], 45, "re-planned epoch")
        print("R0-SAW-REJOIN", flush=True)
        _touch(os.path.join(sync_dir, "r0_done"))
        _wait(lambda: stream.members == [0], 45, "second leave")
        stream.close(final_commit=False)


@pytest.mark.distributed
def test_stream_graceful_leave_and_rejoin(tmp_path):
    from tpusnap.test_utils import run_subprocess_world

    root = str(tmp_path / "stream_elastic")
    sync = str(tmp_path / "sync")
    os.makedirs(sync, exist_ok=True)
    logs = run_subprocess_world(
        _world_stream_leave_rejoin,
        world_size=2,
        args=[root, sync],
        extra_env=_ENV,
        timeout=150,
    )
    joined = "\n".join(logs)
    for marker in ("R1-LEFT", "R1-REJOINED", "R0-SAW-LEAVE", "R0-SAW-REJOIN"):
        assert marker in joined, joined[-4000:]

    # The chain records the resize: one epoch shrank (left [1]), a
    # later one re-grew (joined [1]); restore stays bit-exact.
    from tpusnap import Snapshot, StateDict
    from tpusnap.delta import resolve_chain

    rep = resolve_chain(root)
    assert rep.head and not rep.torn_tail, rep.summary()
    worlds = [m.world for m in rep.members if m.world]
    assert any(w.get("left") == [1] for w in worlds), worlds
    assert any(w.get("joined") == [1] for w in worlds), worlds

    arrays = _state(n=3, seed=5)
    target = {
        "m": StateDict(
            step=0, **{k: np.zeros_like(v) for k, v in arrays.items()}
        )
    }
    Snapshot(rep.head_path).restore(target)
    assert target["m"]["step"] == 1
    for k, v in arrays.items():
        assert np.array_equal(target["m"][k], v), k


# --------------------------------------------------------------------------
# Satellite units: preempt fault kind
# --------------------------------------------------------------------------


def test_preempt_spec_parses():
    from tpusnap.faults import FaultPlan

    plan = FaultPlan.from_spec("preempt=write:3:30")
    assert plan.preempt == ("write", 3, 30.0)
    plan = FaultPlan.from_spec("rank=1,preempt=write:*:5")
    assert plan.preempt == ("write", 0, 5.0)
    assert plan.rank == 1
    with pytest.raises(ValueError):
        FaultPlan.from_spec("preempt=write:3")  # grace_s is required


def test_preempt_delivers_sigterm_once_with_kill_deadline(monkeypatch):
    from tpusnap import faults

    sent = []
    timers = []

    class FakeTimer:
        def __init__(self, interval, fn):
            timers.append(interval)
            self.daemon = False

        def start(self):
            pass

    monkeypatch.setattr(faults.os, "kill", lambda pid, sig: sent.append(sig))
    monkeypatch.setattr(faults.threading, "Timer", FakeTimer)

    plugin = faults.FaultInjectionStoragePlugin(
        inner=None, plan=faults.FaultPlan.from_spec("preempt=write:2:7.5")
    )
    plugin._check_preempt("write")  # attempt 1: not yet
    assert sent == []
    plugin._check_preempt("write")  # attempt 2: SIGTERM + armed SIGKILL
    assert sent == [signal.SIGTERM]
    assert timers == [7.5]
    plugin._check_preempt("write")  # fires at most once
    plugin._check_preempt("write")
    assert sent == [signal.SIGTERM]


# --------------------------------------------------------------------------
# Satellite units: terminal `left` lease state
# --------------------------------------------------------------------------


def test_monitor_never_expires_a_left_rank():
    from tpusnap.dist_store import MemoryKVStore
    from tpusnap.liveness import LeasePublisher, LivenessMonitor

    kv = MemoryKVStore()
    t = [100.0]
    mon = LivenessMonitor(
        kv, "take-x", rank=0, world_size=2, ttl_s=1.0, clock=lambda: t[0]
    )
    p0 = LeasePublisher(kv, "take-x", 0)
    p1 = LeasePublisher(kv, "take-x", 1)
    p0.publish()
    p1.publish()
    mon.check()  # both live
    # Rank 1 leaves gracefully, then goes silent for many TTLs: no
    # expiry, no RankFailedError — and the departure is queryable.
    p1.leave()
    for _ in range(20):
        t[0] += 1.0
        p0.publish()
        mon.check()
    assert mon.left_ranks() == [1]
    assert not mon.dead_ranks()


# --------------------------------------------------------------------------
# Satellite units: slo --check stream-cadence gate
# --------------------------------------------------------------------------


def test_slo_stream_cadence_gate():
    from tpusnap.knobs import override_slo_stream_cadence_x
    from tpusnap.slo import evaluate_records

    now = 1_000_000.0
    rec = {
        "rank": 0,
        "world_size": 2,
        "ts": now - 10.0,
        "last_commit_ts": now - 10.0,
        "stream_cadence_s": 1.0,
    }
    out = evaluate_records([dict(rec)], now=now)
    assert out["verdict"] == "breach", out
    row = out["ranks"][0]
    assert row["breach_stream"] and not row["breach_rpo"], row
    assert "cadence" in out["reason"], out["reason"]
    assert out["thresholds"]["stream_cadence_x"] == 3.0

    # A FINAL record is a clean exit, not a stalled stream.
    out = evaluate_records([dict(rec, final=True)], now=now)
    assert out["verdict"] == "healthy", out
    # Within N x cadence: healthy.
    out = evaluate_records(
        [dict(rec, last_commit_ts=now - 2.0)], now=now
    )
    assert out["verdict"] == "healthy", out
    # Gate off: no stream verdict at all.
    with override_slo_stream_cadence_x(0.0):
        out = evaluate_records([dict(rec)], now=now)
    assert out["verdict"] == "healthy", out
    assert out["thresholds"]["stream_cadence_x"] is None


# --------------------------------------------------------------------------
# Satellite units: chain-report + post-mortem rendering
# --------------------------------------------------------------------------


def test_chain_report_renders_world_and_degraded(capsys):
    from tpusnap.__main__ import _print_chain_report
    from tpusnap.delta import ChainMember, DeltaChainReport

    rep = DeltaChainReport(
        root="/tmp/x",
        members=[
            ChainMember(
                name="base-000000", state="committed", seq=0,
                stream_id="s", world={"size": 2, "ranks": [0, 1]},
            ),
            ChainMember(
                name="delta-000001", state="committed", seq=1,
                parent="base-000000", stream_id="s",
                world={"size": 1, "ranks": [0], "left": [1]},
                degraded={
                    "dead_ranks": [1], "live_ranks": [0],
                    "adopted_units": ["u1", "u2"], "adopters": {"u1": 0},
                },
            ),
            ChainMember(
                name="delta-000002", state="torn", seq=2,
                parent="delta-000001", stream_id="s",
                world={"size": 2, "ranks": [0, 1]}, missing_ranks=[1],
            ),
        ],
        head="delta-000001",
        torn_tail="delta-000002",
        chain=["delta-000001", "base-000000"],
    )
    _print_chain_report(rep)
    out = capsys.readouterr().out
    assert "world 2 (ranks [0, 1])" in out
    assert "left [1]" in out
    assert "DEGRADED: rank(s) [1] died mid-epoch; 2 unit(s) adopted" in out
    assert "journal evidence missing from global rank(s) [1]" in out
    assert "DEGRADED" in rep.summary()
    assert "missing journal evidence from rank(s) [1]" in rep.summary()


def test_postmortem_renders_left_ranks(capsys):
    from tpusnap.__main__ import _render_verdict

    _render_verdict(
        {
            "state": "committed",
            "ranks": {},
            "left_ranks": [1],
            "dead_ranks": None,
        }
    )
    out = capsys.readouterr().out
    assert "LEFT rank(s) [1]" in out
    assert "GRACEFULLY" in out
    assert "DEAD" not in out
