"""Per-dtype serialization round-trips, mirroring the reference's
tests/test_serialization.py:32-101."""

import numpy as np
import pytest

from tpusnap.test_utils import rand_array
from tpusnap.serialization import (
    SUPPORTED_DTYPES,
    Serializer,
    array_as_memoryview,
    array_from_memoryview,
    dtype_itemsize,
    dtype_to_string,
    pickle_as_bytes,
    pickle_from_bytes,
    string_to_dtype,
    tensor_nbytes,
)


@pytest.mark.parametrize("dtype_str", sorted(SUPPORTED_DTYPES))
def test_buffer_roundtrip_bit_identical(dtype_str):
    arr = rand_array(dtype_str)
    mv = array_as_memoryview(arr)
    assert mv.nbytes == arr.nbytes == tensor_nbytes(dtype_str, arr.shape)
    restored = array_from_memoryview(mv, dtype_str, arr.shape)
    assert restored.dtype == arr.dtype
    assert restored.shape == arr.shape
    # bit-identical comparison through raw bytes
    assert bytes(mv) == restored.tobytes() == arr.tobytes()


def test_zero_copy_no_conversion():
    arr = np.arange(1024, dtype=np.float32)
    mv = array_as_memoryview(arr)
    # mutate source; the view must observe it (proof of zero-copy)
    arr[0] = 123.0
    assert np.frombuffer(mv, dtype=np.float32)[0] == 123.0


def test_noncontiguous_copied():
    arr = np.arange(100, dtype=np.int32).reshape(10, 10).T
    mv = array_as_memoryview(arr)
    restored = array_from_memoryview(mv, "int32", (10, 10))
    np.testing.assert_array_equal(restored, np.ascontiguousarray(arr))


def test_empty_array():
    arr = np.zeros((0, 5), dtype=np.float32)
    mv = array_as_memoryview(arr)
    assert mv.nbytes == 0
    restored = array_from_memoryview(mv, "float32", (0, 5))
    assert restored.shape == (0, 5)


def test_bf16_bit_exact():
    import ml_dtypes

    # every possible bf16 bit pattern incl. NaNs/infs round-trips exactly
    bits = np.arange(65536, dtype=np.uint16)
    arr = bits.view(ml_dtypes.bfloat16)
    mv = array_as_memoryview(arr)
    restored = array_from_memoryview(mv, "bfloat16", arr.shape)
    assert restored.tobytes() == arr.tobytes()


def test_dtype_string_tables():
    import jax.numpy as jnp

    for name in ["float32", "bfloat16", "int8", "bool", "complex64"]:
        assert dtype_to_string(string_to_dtype(name)) == name
        assert dtype_itemsize(name) == string_to_dtype(name).itemsize
    # jax dtypes map through numpy
    assert dtype_to_string(jnp.bfloat16) == "bfloat16"
    assert dtype_to_string(jnp.float32) == "float32"
    with pytest.raises(ValueError):
        dtype_to_string(np.dtype("datetime64[s]"))
    with pytest.raises(ValueError):
        string_to_dtype("qint8")


def test_pickle_fallback():
    obj = {"a": [1, 2], "b": {3, 4}, "c": slice(1, 2)}
    assert pickle_from_bytes(pickle_as_bytes(obj)) == obj
    assert Serializer.PICKLE.value == "pickle"


def test_memoryview_stream():
    from tpusnap.memoryview_stream import MemoryviewStream

    data = bytes(range(256))
    s = MemoryviewStream(memoryview(data))
    assert s.read(10) == data[:10]
    assert s.tell() == 10
    s.seek(-6, 2)
    assert s.read() == data[-6:]
    s.seek(0)
    buf = bytearray(300)
    assert s.readinto(buf) == 256
    assert bytes(buf[:256]) == data
    assert len(s) == 256
