"""Cloud storage against REAL server binaries (ROADMAP 5c evidence).

``test_gcs.py``/``test_s3.py`` exercise the plugins against in-process
stubs — fast and deterministic, but the stub only speaks the API subset
its author remembered. This module runs the same plugin + snapshot
round trips against the real ``fake-gcs-server`` and ``minio`` SERVER
BINARIES when they are on PATH (opt-in evidence: each suite skips
cleanly when its binary — or its client package — is missing, so no CI
lane ever fails for lacking them). ``scripts/ci_gate.sh`` runs the
``cloud_real`` marker as an optional step whenever a binary is found.

Server processes are spawned per module, on ephemeral ports, with
filesystem state under pytest's tmp dirs; readiness is polled over the
servers' own health endpoints instead of sleeps.
"""

import os
import shutil
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot
from tpusnap.io_types import ReadIO, WriteIO
from tpusnap.test_utils import find_free_port

_GCS_BINARY = shutil.which("fake-gcs-server")
_MINIO_BINARY = shutil.which("minio")

_MINIO_USER = "tpusnap-ci"
_MINIO_PASSWORD = "tpusnap-ci-secret"


def _wait_http_ready(url: str, timeout_s: float = 30.0) -> None:
    import urllib.error
    import urllib.request

    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                if resp.status < 500:
                    return
        except urllib.error.HTTPError as e:
            if e.code < 500:
                return  # the server answered; 4xx is fine for readiness
            last = e
        except Exception as e:  # noqa: BLE001 - retried until deadline
            last = e
        time.sleep(0.2)
    raise RuntimeError(f"server at {url} never became ready: {last}")


def _terminate(proc: subprocess.Popen) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


def _plugin_round_trip(url: str, storage_options) -> None:
    import asyncio

    from tpusnap.storage_plugin import url_to_storage_plugin_in_event_loop

    loop = asyncio.new_event_loop()
    plugin = url_to_storage_plugin_in_event_loop(url, loop, storage_options)
    try:
        payload = np.arange(100_000, dtype=np.uint8).tobytes()
        plugin.sync_write(WriteIO(path="blob", buf=payload), loop)
        read_io = ReadIO(path="blob")
        plugin.sync_read(read_io, loop)
        assert read_io.buf.getvalue() == payload
        ranged = ReadIO(path="blob", byte_range=(10, 50))
        plugin.sync_read(ranged, loop)
        assert ranged.buf.getvalue() == payload[10:50]
        loop.run_until_complete(plugin.delete("blob"))
    finally:
        plugin.sync_close(loop)
        loop.close()


def _snapshot_round_trip(url: str, storage_options) -> None:
    state = StateDict(
        w=np.random.default_rng(0).standard_normal((256, 32)).astype(np.float32),
        step=7,
    )
    Snapshot.take(url, {"app": state}, storage_options=storage_options)
    assert verify_snapshot(url, storage_options=storage_options).clean
    target = {"app": StateDict(w=np.zeros((256, 32), np.float32), step=0)}
    Snapshot(url, storage_options=storage_options).restore(target)
    assert target["app"]["step"] == 7
    assert np.array_equal(target["app"]["w"], state["w"])


# ------------------------------------------------------- fake-gcs-server


@pytest.fixture(scope="module")
def fake_gcs_endpoint(tmp_path_factory):
    if not _GCS_BINARY:
        pytest.skip("fake-gcs-server binary not on PATH")
    pytest.importorskip("requests")
    port = find_free_port()
    root = tmp_path_factory.mktemp("fake_gcs_data")
    proc = subprocess.Popen(
        [
            _GCS_BINARY,
            "-scheme", "http",
            "-host", "127.0.0.1",
            "-port", str(port),
            "-backend", "filesystem",
            "-filesystem-root", str(root),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    endpoint = f"http://127.0.0.1:{port}"
    try:
        _wait_http_ready(f"{endpoint}/storage/v1/b")
        yield endpoint
    finally:
        _terminate(proc)


def _gcs_bucket(endpoint: str) -> str:
    import requests

    bucket = f"tpusnap-ci-{uuid.uuid4().hex[:8]}"
    resp = requests.post(
        f"{endpoint}/storage/v1/b", json={"name": bucket}, timeout=10
    )
    assert resp.status_code in (200, 409), resp.text
    return bucket


@pytest.mark.cloud_real
class TestRealFakeGCSServer:
    def test_plugin_round_trip(self, fake_gcs_endpoint):
        bucket = _gcs_bucket(fake_gcs_endpoint)
        _plugin_round_trip(
            f"gs://{bucket}/plugin",
            {"api_endpoint": fake_gcs_endpoint},
        )

    def test_snapshot_round_trip(self, fake_gcs_endpoint):
        bucket = _gcs_bucket(fake_gcs_endpoint)
        _snapshot_round_trip(
            f"gs://{bucket}/snap",
            {"api_endpoint": fake_gcs_endpoint},
        )


# ------------------------------------------------------------------ minio


@pytest.fixture(scope="module")
def minio_endpoint(tmp_path_factory):
    if not _MINIO_BINARY:
        pytest.skip("minio binary not on PATH")
    pytest.importorskip("aiobotocore")
    port = find_free_port()
    root = tmp_path_factory.mktemp("minio_data")
    proc = subprocess.Popen(
        [
            _MINIO_BINARY,
            "server", str(root),
            "--address", f"127.0.0.1:{port}",
            "--console-address", f"127.0.0.1:{find_free_port()}",
        ],
        env=dict(
            os.environ,
            MINIO_ROOT_USER=_MINIO_USER,
            MINIO_ROOT_PASSWORD=_MINIO_PASSWORD,
        ),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    endpoint = f"http://127.0.0.1:{port}"
    try:
        _wait_http_ready(f"{endpoint}/minio/health/live")
        yield endpoint
    finally:
        _terminate(proc)


def _minio_options(endpoint: str):
    return {
        "client_kwargs": {
            "endpoint_url": endpoint,
            "aws_access_key_id": _MINIO_USER,
            "aws_secret_access_key": _MINIO_PASSWORD,
            "region_name": "us-east-1",
        }
    }


def _minio_bucket(endpoint: str) -> str:
    import asyncio

    from aiobotocore.session import get_session

    bucket = f"tpusnap-ci-{uuid.uuid4().hex[:8]}"

    async def create():
        session = get_session()
        async with session.create_client(
            "s3", **_minio_options(endpoint)["client_kwargs"]
        ) as client:
            await client.create_bucket(Bucket=bucket)

    asyncio.run(create())
    return bucket


@pytest.mark.cloud_real
class TestRealMinIO:
    def test_plugin_round_trip(self, minio_endpoint):
        bucket = _minio_bucket(minio_endpoint)
        _plugin_round_trip(
            f"s3://{bucket}/plugin", _minio_options(minio_endpoint)
        )

    def test_snapshot_round_trip(self, minio_endpoint):
        bucket = _minio_bucket(minio_endpoint)
        _snapshot_round_trip(
            f"s3://{bucket}/snap", _minio_options(minio_endpoint)
        )


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v", "-m", "cloud_real"]))
