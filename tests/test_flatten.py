"""Flatten/inflate round-trips incl. hostile keys, mirroring the
reference's tests/test_flatten.py."""

from collections import OrderedDict

import numpy as np
import pytest

from tpusnap.flatten import flatten, inflate
from tpusnap.manifest import DictEntry, ListEntry, TupleEntry


def _roundtrip(obj, prefix="root"):
    manifest, flattened = flatten(obj, prefix=prefix)
    return inflate(manifest, flattened, prefix=prefix)


def test_simple_dict():
    obj = {"a": 1, "b": {"c": 2, "d": [3, 4, {"e": 5}]}}
    assert _roundtrip(obj) == obj


def test_hostile_keys():
    obj = {
        "with/slash": 1,
        "with%percent": 2,
        "with/both%25": 3,
        "": 4,
        "ünïcödé/äöü": 5,
    }
    assert _roundtrip(obj) == obj


def test_int_keys_preserved():
    obj = {0: "zero", 1: {"nested": 2}, "s": 3}
    out = _roundtrip(obj)
    assert out == obj
    assert set(map(type, out.keys())) == {int, str}


def test_colliding_keys_not_flattened():
    obj = {"outer": {1: "int-one", "1": "str-one"}}
    manifest, flattened = flatten(obj, prefix="p")
    # Colliding dict must be kept whole as one leaf.
    assert "p/outer" in flattened
    assert flattened["p/outer"] == {1: "int-one", "1": "str-one"}
    assert _roundtrip(obj) == obj


def test_non_str_int_keys_not_flattened():
    obj = {"outer": {(1, 2): "tuple-key"}}
    manifest, flattened = flatten(obj, prefix="p")
    assert flattened["p/outer"] == {(1, 2): "tuple-key"}
    assert _roundtrip(obj) == obj


def test_ordered_dict_preserved():
    od = OrderedDict([("z", 1), ("a", 2), ("m", [1, 2])])
    out = _roundtrip(od)
    assert isinstance(out, OrderedDict)
    assert list(out.keys()) == ["z", "a", "m"]
    assert out == od


def test_tuple_and_namedtuple():
    obj = {"opt": (1, (2, 3), [4, (5,)])}
    out = _roundtrip(obj)
    assert out == obj
    assert isinstance(out["opt"], tuple)
    assert isinstance(out["opt"][1], tuple)
    assert isinstance(out["opt"][2][1], tuple)


def test_list_ordering_beyond_ten():
    obj = {"l": list(range(15))}
    out = _roundtrip(obj)
    assert out["l"] == list(range(15))


def test_leaves_are_not_copied():
    arr = np.arange(10)
    obj = {"x": arr}
    manifest, flattened = flatten(obj, prefix="r")
    assert flattened["r/x"] is arr


def test_manifest_entries():
    obj = {"d": {"l": [1], "t": (2,)}}
    manifest, flattened = flatten(obj, prefix="r")
    assert isinstance(manifest["r"], DictEntry)
    assert isinstance(manifest["r/d"], DictEntry)
    assert isinstance(manifest["r/d/l"], ListEntry)
    assert isinstance(manifest["r/d/t"], TupleEntry)
    assert flattened == {"r/d/l/0": 1, "r/d/t/0": 2}


def test_root_leaf():
    manifest, flattened = flatten(42, prefix="r")
    assert manifest == {}
    assert flattened == {"r": 42}
    assert inflate(manifest, flattened, prefix="r") == 42


def test_empty_containers():
    obj = {"e": {}, "l": [], "t": ()}
    out = _roundtrip(obj)
    assert out == obj
    assert isinstance(out["t"], tuple)


def test_inflate_drops_missing_leaves():
    obj = {"a": 1, "b": 2}
    manifest, flattened = flatten(obj, prefix="r")
    del flattened["r/b"]
    out = inflate(manifest, flattened, prefix="r")
    assert out == {"a": 1}


def test_bad_prefix_raises():
    manifest, flattened = flatten({"a": 1}, prefix="r")
    with pytest.raises(ValueError):
        inflate(manifest, flattened, prefix="nope")


def test_missing_leaf_with_tuple_in_list_compacts():
    # Regression: missing leaves in list/tuple containers must compact
    # without corrupting sibling tuples.
    m, f = flatten({"l": [1, 2, (3,)]}, prefix="r")
    del f["r/l/0"]
    assert inflate(m, f, prefix="r") == {"l": [2, (3,)]}
    m, f = flatten({"l": [1, (2,), 3]}, prefix="r")
    del f["r/l/0"]
    assert inflate(m, f, prefix="r") == {"l": [(2,), 3]}


def test_inflate_drops_keys_absent_from_container_entry():
    # The container entry is the source of truth for dict membership.
    from tpusnap.manifest import DictEntry

    m = {"r": DictEntry(keys=["a"])}
    f = {"r/a": 1, "r/b": 2}
    assert inflate(m, f, prefix="r") == {"a": 1}
