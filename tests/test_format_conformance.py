"""docs/format.md conformance: a third-party reader using ONLY the
documented on-disk format (json + raw file reads + numpy/ml_dtypes —
none of tpusnap's read machinery) must be able to reconstruct every
array class a snapshot stores: dense, slab member, sharded, chunked,
primitive, and incremental '../' references.

This is the proof that the format spec is the actual contract, not
aspirational documentation.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from tpusnap import Snapshot, StateDict, PytreeState
from tpusnap.knobs import (
    override_batching_disabled,
    override_max_chunk_size_bytes,
)

import ml_dtypes

_DTYPES = {
    "float32": np.float32,
    "float64": np.float64,
    "bfloat16": ml_dtypes.bfloat16,
    "int32": np.int32,
    "uint16": np.uint16,
}


def _read_blob(root: str, location: str, byte_range=None) -> bytes:
    """Raw blob read per the spec: location resolved against the root
    with POSIX normpath (incremental '../' references), optional
    [start, end) byte range."""
    path = os.path.normpath(os.path.join(root, location))
    with open(path, "rb") as f:
        data = f.read()
    if byte_range is not None:
        data = data[byte_range[0] : byte_range[1]]
    return data


def _tensor_from_entry(root: str, e: dict) -> np.ndarray:
    data = _read_blob(root, e["location"], e.get("byte_range"))
    # Verify per spec: crc32c is the native Castagnoli; a zlib-crc32
    # algo (fallback build) would be skipped — this suite runs native.
    algo, _, value = e["checksum"].partition(":")
    if algo == "crc32c":
        from tpusnap import _native

        assert _native.crc32c(data) == int(value, 16), e["location"]
    arr = np.frombuffer(data, dtype=_DTYPES[e["dtype"]])
    return arr.reshape(e["shape"])


def _external_reader(root: str):
    md = json.load(open(os.path.join(root, ".snapshot_metadata")))
    # Required keys per spec; other fields (created_at, future additions)
    # are optional-and-ignorable.
    assert {"version", "world_size", "manifest"} <= set(md)

    def read(path: str):
        e = md["manifest"][path]
        if e["type"] == "primitive":
            if e["dtype"] == "float":
                import base64
                import struct

                return struct.unpack(
                    "<d", base64.b64decode(e["serialized_value"])
                )[0]
            if e["dtype"] == "int":
                return int(e["serialized_value"])
            return e["serialized_value"]
        if e["type"] == "Tensor":
            return _tensor_from_entry(root, e)
        if e["type"] == "ChunkedTensor":
            out = np.empty(e["shape"], dtype=_DTYPES[e["dtype"]])
            for c in e["chunks"]:
                r0 = c["offsets"][0]
                out[r0 : r0 + c["sizes"][0]] = _tensor_from_entry(
                    root, c["tensor"]
                )
            return out
        if e["type"] == "Sharded":
            out = np.empty(e["shape"], dtype=_DTYPES[e["dtype"]])
            for s in e["shards"]:
                idx = tuple(
                    slice(o, o + n) for o, n in zip(s["offsets"], s["sizes"])
                )
                out[idx] = _tensor_from_entry(root, s["tensor"])
            return out
        raise AssertionError(f"unhandled entry type {e['type']}")

    return md, read


def test_external_reader_reconstructs_everything(tmp_path):
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("x", "y"))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x", "y"))
    rng = np.random.default_rng(0)
    dense = rng.standard_normal((128, 64)).astype(np.float32)
    small_a = np.arange(64, dtype=np.float32)  # slab members
    small_b = np.arange(64, 128, dtype=np.float32)
    bf = rng.standard_normal((16, 16)).astype(ml_dtypes.bfloat16)
    sharded = jax.device_put(
        jnp.arange(32 * 32, dtype=jnp.float32).reshape(32, 32), sh
    )
    chunky = rng.standard_normal((64, 32)).astype(np.float32)

    path = str(tmp_path / "snap")
    with override_max_chunk_size_bytes(2048):
        Snapshot.take(
            path,
            {
                "m": PytreeState({"w": sharded}),
                "t": StateDict(
                    dense=dense,
                    a=small_a,
                    b=small_b,
                    bf=bf,
                    chunky=chunky,
                    step=7,
                    lr=2.5,
                    tag="hello",
                ),
            },
        )

    md, read = _external_reader(path)
    assert md["world_size"] == 1
    assert np.array_equal(read("0/t/dense"), dense)
    assert np.array_equal(read("0/t/a"), small_a)  # slab byte_range
    assert np.array_equal(read("0/t/b"), small_b)
    assert read("0/t/bf").tobytes() == bf.tobytes()
    assert np.array_equal(read("0/t/chunky"), chunky)  # chunk reassembly
    assert np.array_equal(read("0/m/w"), np.asarray(sharded))  # shard scatter
    assert read("0/t/step") == 7
    assert read("0/t/lr") == 2.5
    assert read("0/t/tag") == "hello"


def test_external_reader_follows_incremental_references(tmp_path):
    st = StateDict(w=np.random.default_rng(1).standard_normal((256, 16)).astype(np.float32))
    base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
    with override_batching_disabled(True):
        Snapshot.take(base, {"app": st})
        Snapshot.take(inc, {"app": st}, incremental_from=base)
    md, read = _external_reader(inc)
    e = md["manifest"]["0/app/w"]
    assert e["location"].startswith("../"), e["location"]
    assert np.array_equal(read("0/app/w"), st["w"])


def test_tile_checksums_fold_per_spec(tmp_path):
    """tile_checksums: whole-blob value equals the CRC-combine fold of
    the per-tile values (spec's sub-range verification contract)."""
    from tpusnap import _native
    from tpusnap.knobs import override_tile_checksum_bytes

    arr = np.random.default_rng(2).standard_normal((4096, 16)).astype(np.float32)
    path = str(tmp_path / "snap")
    with override_tile_checksum_bytes(64 * 1024), override_batching_disabled(True):
        Snapshot.take(path, {"app": StateDict(big=arr)})
    e = json.load(open(os.path.join(path, ".snapshot_metadata")))["manifest"][
        "0/app/big"
    ]
    tiles = e["tile_checksums"]
    assert len(tiles) > 1
    row_nbytes = arr.nbytes // arr.shape[0]
    t = e["tile_rows"]
    # Algorithm-agnostic: the fold identity holds for whichever
    # implementation this build records (crc32c native / zlib fallback).
    algo = _native.checksum_algorithm()
    combined = None
    for i, ts in enumerate(tiles):
        tile_algo, _, value = ts.partition(":")
        assert tile_algo == algo
        crc = int(value, 16)
        r1 = min((i + 1) * t, arr.shape[0])
        nb = (r1 - i * t) * row_nbytes
        combined = (
            crc if combined is None else _native.crc_combine(combined, crc, nb)
        )
    assert f"{algo}:{combined:08x}" == e["checksum"]


def test_unknown_fields_are_ignorable(tmp_path):
    """Forward compatibility per the spec: a snapshot written by a future
    tpusnap with extra entry/metadata fields must load with this one."""
    path = str(tmp_path / "snap")
    with override_batching_disabled(True):
        Snapshot.take(
            path, {"a": StateDict(w=np.arange(64, dtype=np.float32), n=3)}
        )
    meta_path = os.path.join(path, ".snapshot_metadata")
    md = json.load(open(meta_path))
    md["future_top_level"] = {"x": 1}
    for e in md["manifest"].values():
        e["future_field"] = "ignored"
    # Per the format spec: a tool that rewrites the metadata must strip
    # (or recompute) self_checksum — it covers the exact file bytes.
    md.pop("self_checksum", None)
    json.dump(md, open(meta_path, "w"))

    target = {"a": StateDict(w=np.zeros(64, np.float32), n=0)}
    Snapshot(path).restore(target)
    assert np.array_equal(target["a"]["w"], np.arange(64, dtype=np.float32))
    assert target["a"]["n"] == 3
    from tpusnap import verify_snapshot

    assert verify_snapshot(path).clean


def _xxh64_pure(data: bytes, seed: int = 0) -> int:
    """Independent pure-Python XXH64 (reference algorithm) so the
    conformance check does not trust the native implementation it is
    verifying."""
    M = (1 << 64) - 1
    P1, P2, P3 = 11400714785074694791, 14029467366897019727, 1609587929392839161
    P4, P5 = 9650029242287828579, 2870177450012600261
    rotl = lambda x, r: ((x << r) | (x >> (64 - r))) & M  # noqa: E731

    def rnd(acc, lane):
        return (rotl((acc + lane * P2) & M, 31) * P1) & M

    n, i = len(data), 0
    if n >= 32:
        v = [(seed + P1 + P2) & M, (seed + P2) & M, seed & M, (seed - P1) & M]
        while n - i >= 32:
            for k in range(4):
                v[k] = rnd(v[k], int.from_bytes(data[i + 8 * k : i + 8 * k + 8], "little"))
            i += 32
        h = (rotl(v[0], 1) + rotl(v[1], 7) + rotl(v[2], 12) + rotl(v[3], 18)) & M
        for k in range(4):
            h = ((h ^ rnd(0, v[k])) * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while n - i >= 8:
        h = (rotl(h ^ rnd(0, int.from_bytes(data[i : i + 8], "little")), 27) * P1 + P4) & M
        i += 8
    if n - i >= 4:
        h = (rotl(h ^ (int.from_bytes(data[i : i + 4], "little") * P1) & M, 23) * P2 + P3) & M
        i += 4
    while i < n:
        h = (rotl(h ^ (data[i] * P5) & M, 11) * P1) & M
        i += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    h ^= h >> 32
    return h


def test_dedup_hashes_recomputable_per_spec(tmp_path):
    """format.md: dedup_hash = "<algo>:<16-hex>" over the same bytes as
    checksum; xxh64 is seed-0 XXH64, sha256-64 is the first 8 bytes of
    SHA-256 big-endian; tile_dedup_hashes tile like tile_checksums. An
    external reader recomputes every recorded value from the raw blob
    bytes alone."""
    import hashlib

    from tpusnap.knobs import (
        override_batching_disabled,
        override_record_dedup_hashes,
        override_tile_checksum_bytes,
    )

    rng = np.random.default_rng(23)
    state = StateDict(
        big=rng.standard_normal((512, 32)).astype(np.float32),
        small=rng.standard_normal(40).astype(np.float32),
        cfg={"a": [1, 2]},
    )
    path = str(tmp_path / "s")
    with override_batching_disabled(True), override_tile_checksum_bytes(
        8 * 1024
    ), override_record_dedup_hashes(True):
        Snapshot.take(path, {"app": state})

    md = json.loads(open(os.path.join(path, ".snapshot_metadata")).read())

    def recompute(algo: str, raw: bytes) -> str:
        if algo == "xxh64":
            return f"{_xxh64_pure(raw):016x}"
        assert algo == "sha256-64"
        return hashlib.sha256(raw).digest()[:8].hex()

    checked = 0
    for key, entry in md["manifest"].items():
        if entry.get("dedup_hash"):
            raw = open(os.path.join(path, entry["location"]), "rb").read()
            if entry.get("byte_range"):
                s, e = entry["byte_range"]
                raw = raw[s:e]
            algo, _, val = entry["dedup_hash"].partition(":")
            assert val == recompute(algo, raw), key
            checked += 1
        if entry.get("tile_dedup_hashes"):
            raw = open(os.path.join(path, entry["location"]), "rb").read()
            t = entry["tile_rows"]
            n_rows = entry["shape"][0]
            row_nbytes = len(raw) // n_rows
            for i, th in enumerate(entry["tile_dedup_hashes"]):
                r0, r1 = i * t, min((i + 1) * t, n_rows)
                algo, _, val = th.partition(":")
                assert val == recompute(
                    algo, raw[r0 * row_nbytes : r1 * row_nbytes]
                ), (key, i)
                checked += 1
    assert checked > 2
