"""Sharded embedding-collection tests — the torchrec parity matrix.

Mirrors the reference's torchrec coverage
(/root/reference/tests/gpu_tests/test_torchrec.py:181-304): src×dst
sharding-type matrix (row/col/table), sync and async snapshots, fused
(row-wise Adagrad) optimizer state round-trip, shard subdivision via a
shrunken max-shard knob, and UVM-analog host-offloaded tables. Runs on
the 8-device CPU mesh from conftest."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpusnap import PytreeState, Snapshot
from tpusnap.knobs import override_max_shard_size_bytes
from tpusnap.models import (
    EmbeddingCollection,
    TableConfig,
    make_embedding_train_step,
    make_mesh,
)
from tpusnap.models.embedding import rand_features

SHARDINGS = ("row", "col", "table")


def _tables(sharding: str, host_offload: bool = False):
    # "table" groups need >= 2 same-shape tables to be interesting; use 4
    # so the stacked [4, V, D] group shards 4-ways over ("fsdp","tensor").
    return [
        TableConfig(f"t{i}", 64, 16, sharding=sharding,
                    host_offload=host_offload,
                    pooling="mean" if i % 2 else "sum")
        for i in range(4)
    ]


def _gather(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestEmbeddingCollection:
    def test_forward_shapes_and_masking(self):
        model = EmbeddingCollection(_tables("row"))
        params = model.init(jax.random.PRNGKey(0))
        feats, _ = rand_features(model, None, batch=8, bag=5)
        out = model.apply(params, feats)
        assert out.shape == (8, 4 * 16)
        # all-padding bag contributes exactly zero (sum pooling, table t0)
        feats["t0"] = jnp.full_like(feats["t0"], -1)
        out2 = model.apply(params, feats)
        np.testing.assert_allclose(np.asarray(out2[:, :16]), 0.0)

    @pytest.mark.parametrize("sharding", SHARDINGS)
    def test_train_step_decreases_loss(self, sharding):
        mesh = make_mesh(jax.devices())
        model = EmbeddingCollection(_tables(sharding))
        params = model.shard_params(model.init(jax.random.PRNGKey(0)), mesh)
        step = make_embedding_train_step(model, mesh)
        feats, targets = rand_features(model, mesh, batch=8, bag=5)
        _, loss0 = step(params, feats, targets)
        for _ in range(5):
            params, loss = step(params, feats, targets)
        assert float(loss) < float(loss0)
        # Adagrad accumulators actually accumulated
        assert all(float(jnp.max(a)) > 0 for a in params["opt"].values())


class TestEmbeddingReshardingMatrix:
    """Save under sharding A, restore under sharding B — all 9 pairs,
    sync and async (reference test_torchrec.py's core matrix)."""

    @pytest.mark.parametrize("src", SHARDINGS)
    @pytest.mark.parametrize("dst", SHARDINGS)
    @pytest.mark.parametrize("use_async", [False, True], ids=["sync", "async"])
    def test_src_dst(self, tmp_path, src, dst, use_async):
        mesh = make_mesh(jax.devices())
        src_model = EmbeddingCollection(_tables(src))
        params = src_model.shard_params(
            src_model.init(jax.random.PRNGKey(7)), mesh
        )
        # One optimizer step so opt state is non-trivial before saving.
        step = make_embedding_train_step(src_model, mesh)
        feats, targets = rand_features(src_model, mesh, batch=8, bag=5)
        params, _ = step(params, feats, targets)
        expected_out = np.asarray(src_model.apply(params, feats))

        path = str(tmp_path / "snap")
        app = {"emb": PytreeState(params)}
        if use_async:
            Snapshot.async_take(path, app).wait()
        else:
            Snapshot.take(path, app)

        dst_model = EmbeddingCollection(_tables(dst))
        dst_params = dst_model.shard_params(
            jax.tree.map(jnp.zeros_like, dst_model.init(jax.random.PRNGKey(0))),
            mesh,
        )
        # The pytree *structure* differs between table-grouped and
        # per-table layouts; restore leaf-by-leaf through dense views.
        target = PytreeState(dst_params)
        if src == dst:
            Snapshot(path).restore({"emb": target})
            restored = target.tree
            _assert_tree_equal(_dense_view(src_model, params),
                               _dense_view(dst_model, restored))
            np.testing.assert_array_equal(
                np.asarray(dst_model.apply(restored, feats)), expected_out
            )
        else:
            # Cross-layout: read each table as a dense array (random
            # access) and re-place under the destination sharding — the
            # user-level recipe for changing sharding *taxonomy* (not just
            # mesh split), reference read_object analog.
            snap = Snapshot(path)
            dense_src = _read_dense(snap, src_model)
            placed = _place_dense(dst_model, dense_src, mesh)
            _assert_tree_equal(_dense_view(src_model, params),
                               _dense_view(dst_model, placed))
            np.testing.assert_array_equal(
                np.asarray(dst_model.apply(placed, feats)), expected_out
            )


def _dense_view(model, params):
    """{table_name: [V, D]} regardless of grouping; opt as {name: [V]}."""
    out = {}
    for t in model.tables:
        out[t.name] = np.asarray(model._table_weight(params, t))
        if t.sharding == "table":
            g = model._group_key(t)
            idx = next(
                i for i, m in enumerate(model._groups[g]) if m.name == t.name
            )
            out["opt/" + t.name] = np.asarray(params["opt"][g][idx])
        else:
            out["opt/" + t.name] = np.asarray(params["opt"][t.name])
    return out


def _read_dense(snap, model):
    dense = {}
    for key in model.param_specs()["tables"]:
        dense["tables/" + key] = snap.read_object(f"0/emb/tables/{key}")
        dense["opt/" + key] = snap.read_object(f"0/emb/opt/{key}")
    # Un-group into per-table dense arrays.
    out = {}
    for t in model.tables:
        if t.sharding == "table":
            g = model._group_key(t)
            idx = next(
                i for i, m in enumerate(model._groups[g]) if m.name == t.name
            )
            out[t.name] = np.asarray(dense["tables/" + g])[idx]
            out["opt/" + t.name] = np.asarray(dense["opt/" + g])[idx]
        else:
            out[t.name] = np.asarray(dense["tables/" + t.name])
            out["opt/" + t.name] = np.asarray(dense["opt/" + t.name])
    return out


def _place_dense(model, dense, mesh):
    specs = model.param_specs()
    params = {"tables": {}, "opt": {}}
    for key, spec in specs["tables"].items():
        if key.startswith("group_"):
            members = model._groups[key]
            w = np.stack([dense[m.name] for m in members])
            acc = np.stack([dense["opt/" + m.name] for m in members])
        else:
            w = dense[key]
            acc = dense["opt/" + key]
        params["tables"][key] = jax.device_put(
            jnp.asarray(w), NamedSharding(mesh, spec)
        )
        params["opt"][key] = jax.device_put(
            jnp.asarray(acc), NamedSharding(mesh, specs["opt"][key])
        )
    return params


class TestEmbeddingKnobsAndOffload:
    def test_shard_subdivision(self, tmp_path):
        """Max-shard knob below one shard forces subdivision on save
        (reference shrinks max shard below smallest shard,
        test_torchrec.py:215-225)."""
        mesh = make_mesh(jax.devices())
        model = EmbeddingCollection(_tables("row"))
        params = model.shard_params(model.init(jax.random.PRNGKey(1)), mesh)
        path = str(tmp_path / "snap")
        # each addressable shard is 16*16*4 = 1 KiB; force ≤ 256 B pieces
        with override_max_shard_size_bytes(256):
            Snapshot.take(path, {"emb": PytreeState(params)})
        target = PytreeState(
            model.shard_params(
                jax.tree.map(jnp.zeros_like, model.init(jax.random.PRNGKey(0))),
                mesh,
            )
        )
        Snapshot(path).restore({"emb": target})
        _assert_tree_equal(_gather(params), _gather(target.tree))

    def test_host_offloaded_tables_roundtrip(self, tmp_path):
        """UVM analog: host-offloaded tables snapshot and restore like any
        other sharded array (no-op offload on backends without host
        memory kinds)."""
        mesh = make_mesh(jax.devices())
        model = EmbeddingCollection(_tables("row", host_offload=True))
        params = model.shard_params(model.init(jax.random.PRNGKey(2)), mesh)
        path = str(tmp_path / "snap")
        Snapshot.take(path, {"emb": PytreeState(params)})
        target = PytreeState(
            model.shard_params(
                jax.tree.map(jnp.zeros_like, model.init(jax.random.PRNGKey(0))),
                mesh,
            )
        )
        Snapshot(path).restore({"emb": target})
        _assert_tree_equal(_gather(params), _gather(target.tree))

    def test_restore_into_smaller_mesh(self, tmp_path):
        """Elasticity across mesh *shape*: save on (2,2,2), restore on a
        (1,2,1) two-device mesh."""
        mesh8 = make_mesh(jax.devices())
        model = EmbeddingCollection(_tables("row"))
        params = model.shard_params(model.init(jax.random.PRNGKey(3)), mesh8)
        path = str(tmp_path / "snap")
        Snapshot.take(path, {"emb": PytreeState(params)})
        mesh2 = Mesh(
            np.asarray(jax.devices()[:2]).reshape(1, 2, 1),
            ("data", "fsdp", "tensor"),
        )
        target = PytreeState(
            model.shard_params(
                jax.tree.map(jnp.zeros_like, model.init(jax.random.PRNGKey(0))),
                mesh2,
            )
        )
        Snapshot(path).restore({"emb": target})
        _assert_tree_equal(_gather(params), _gather(target.tree))


class TestEmbeddingIncremental:
    """The motivating incremental case: large embedding tables that
    didn't train this interval stop costing I/O (incl. host-offloaded
    ones — the UVM-analog tables)."""

    @pytest.mark.parametrize("host_offload", [False, True],
                             ids=["device", "offloaded"])
    def test_frozen_tables_dedup(self, tmp_path, host_offload):
        from tpusnap import verify_snapshot

        mesh = make_mesh(jax.devices())
        model = EmbeddingCollection(_tables("row", host_offload=host_offload))
        params = model.shard_params(
            model.init(jax.random.PRNGKey(3)), mesh
        )
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        Snapshot.take(base, {"emb": PytreeState(params)})
        # No training between snapshots: the tables are unchanged.
        Snapshot.take(
            inc, {"emb": PytreeState(params)}, incremental_from=base
        )
        import os

        blobs = [
            f
            for d, _, fs in os.walk(inc)
            for f in fs
            if f != ".snapshot_metadata" and ".tpusnap" not in d.split(os.sep)
        ]
        assert blobs == [], blobs
        assert verify_snapshot(inc).clean
        target = model.shard_params(
            jax.tree.map(jnp.zeros_like, model.init(jax.random.PRNGKey(0))),
            mesh,
        )
        tgt_state = PytreeState(target)
        Snapshot(inc).restore({"emb": tgt_state})
        _assert_tree_equal(_gather(tgt_state.tree), _gather(params))

    def test_trained_tables_rewrite(self, tmp_path):
        mesh = make_mesh(jax.devices())
        model = EmbeddingCollection(_tables("row"))
        params = model.shard_params(model.init(jax.random.PRNGKey(3)), mesh)
        base, inc = str(tmp_path / "s0"), str(tmp_path / "s1")
        Snapshot.take(base, {"emb": PytreeState(params)})
        step = make_embedding_train_step(model, mesh)
        feats, targets = rand_features(model, mesh, batch=8, bag=5)
        params2, _ = step(params, feats, targets)
        Snapshot.take(
            inc, {"emb": PytreeState(params2)}, incremental_from=base
        )
        import os

        blobs = [
            f
            for d, _, fs in os.walk(inc)
            for f in fs
            if f != ".snapshot_metadata" and ".tpusnap" not in d.split(os.sep)
        ]
        assert blobs, "a training step must rewrite the touched shards"
        target = model.shard_params(
            jax.tree.map(jnp.zeros_like, model.init(jax.random.PRNGKey(0))),
            mesh,
        )
        tgt_state = PytreeState(target)
        Snapshot(inc).restore({"emb": tgt_state})
        _assert_tree_equal(_gather(tgt_state.tree), _gather(params2))


def test_host_resident_arrays_still_clone_on_async_take():
    """_may_alias_live_memory: device arrays on non-CPU backends skip
    the async defensive clone (their host copy cannot alias donated
    HBM), but host-RESIDENT (pinned_host, the UVM analog) arrays alias
    host memory on any backend and must keep cloning — as must CPU
    device arrays and plain numpy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpusnap.host_offload import supports_host_offload, to_host_offload
    from tpusnap.io_preparers.array import _may_alias_live_memory

    arr_np = np.arange(8, dtype=np.float32)
    assert _may_alias_live_memory(arr_np, arr_np)
    dev = jnp.arange(8, dtype=jnp.float32)  # CPU backend in tests
    assert _may_alias_live_memory(dev, np.asarray(dev))
    if supports_host_offload():
        offl = to_host_offload(dev)
        assert _may_alias_live_memory(offl, np.asarray(offl))
