"""Access-ledger tests: in-memory aggregation + working-set union,
flush/rotation/torn-tail crash tolerance, the knob gate, the two
acceptance coverage shapes (read_object of 2-of-20 leaves → coverage
< 0.2 naming exactly the read leaves; full restore → ≈1.0), the
many-reader concurrency soak (whole interleaved lines, merged heatmap
bytes == Σ per-reader ``storage.bytes_read``), the ≤10% restore
overhead guard with the ledger ON, the fleet reader fold/gate/prom
families, the analyze ``partial_access`` finding, the tune
working-set restore-budget rule, cold-first ``gc --evict-local``
ordering, and the ``heatmap`` CLI exit contract (0/2/3).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, knobs
from tpusnap import access
from tpusnap.__main__ import _heatmap_metadata, main
from tpusnap.access import (
    AccessLedger,
    compute_heatmap,
    load_ledger_records,
    location_read_counts,
)
from tpusnap.analyze import Thresholds, access_findings
from tpusnap.fleet import (
    evaluate_fleet,
    fold_fleet,
    note_reader_scope,
    read_fleet_records,
    render_fleet_prom,
    reset_publisher,
    reset_reader_stats,
)
from tpusnap.history import load_history
from tpusnap.io_types import StoragePlugin
from tpusnap.knobs import (
    override_access_ledger,
    override_access_ledger_max_bytes,
    override_fleet_dir,
    override_job_id,
    override_telemetry_dir,
)
from tpusnap.lifecycle import gc_snapshot
from tpusnap.metrics_export import parse_prometheus_textfile
from tpusnap.tiering import drain_snapshot, parse_tier_url
from tpusnap.tune import build_plan

MiB = 1 << 20
GiB = 1 << 30


@pytest.fixture
def tele_env(tmp_path):
    with override_telemetry_dir(str(tmp_path / "tele")):
        yield str(tmp_path / "tele")


# ------------------------------------------------------- ledger unit


def test_ledger_buckets_aggregate_and_working_set(tmp_path, tele_env):
    led = AccessLedger(str(tmp_path / "snap"))
    for _ in range(3):
        led.record("m/w0", "0/blob", 0, 100, 100)
    led.record("m/w0", "0/blob", 50, 200, 150)
    led.record("m/w1", "0/blob2", 0, 10, 10, source="cas")
    assert led.total_reads == 5
    assert led.total_bytes == 460
    # Union per location: [0,200) on blob + [0,10) on blob2.
    assert led.working_set_bytes() == 210
    led.flush()
    recs = load_ledger_records(str(tmp_path / "snap"))
    # Bounded: 3 identical reads are ONE record with n=3, not 3 lines.
    assert len(recs) == 3
    by = {(r["lp"], tuple(r["range"])): r for r in recs}
    assert by[("m/w0", (0, 100))]["n"] == 3
    assert by[("m/w0", (0, 100))]["bytes"] == 300
    assert by[("m/w1", (0, 10))]["src"] == "cas"
    # Scope totals survive the flush (the fleet reader record and the
    # restore summary read them after the buckets drained to disk).
    assert led.total_bytes == 460 and led.total_reads == 5


def test_ledger_torn_tail_skipped_and_rotation(tmp_path, tele_env):
    snap = str(tmp_path / "snap")
    led = AccessLedger(snap)
    led.record("m/w0", "0/blob", 0, 100, 100)
    led.flush()
    # Torn tail (killed mid-append): the partial line is skipped.
    with open(led.path, "ab") as f:
        f.write(b'{"v":1,"lp":"m/w1","byt')
    assert [r["lp"] for r in load_ledger_records(snap)] == ["m/w0"]
    # Rotation: past the bound (floored at 64 KiB so a misconfigured
    # knob can't rotate every flush) the file moves to `.1`; both
    # generations load (rotated first, roughly chronological).
    big = AccessLedger(snap)
    for i in range(1200):  # ~130 KB of distinct buckets
        big.record("m/w1", "0/blob", i * 100, i * 100 + 100, 100)
    big.flush()
    with override_access_ledger_max_bytes(1):
        led2 = AccessLedger(snap)
        led2.record("m/w2", "0/blob", 0, 5, 5)
        led2.flush()
    assert os.path.exists(led.path + ".1")
    assert {r["lp"] for r in load_ledger_records(snap)} == {
        "m/w0",
        "m/w1",
        "m/w2",
    }


def test_read_scope_gated_by_knob_and_ambient(tmp_path, tele_env):
    snap = str(tmp_path / "snap")
    with override_access_ledger(False):
        with access.read_scope(snap) as led:
            assert led is None
            assert access.current() is None
    assert not os.path.isdir(os.path.join(tele_env, "access"))
    with access.read_scope(snap, default_source="remote") as led:
        assert access.current() is led
        led.record("m/w", "0/b", 0, 8, 8)
    assert access.current() is None
    recs = load_ledger_records(snap)
    assert recs and recs[0]["src"] == "remote"


# --------------------------------------- acceptance: coverage shapes


def test_read_object_partial_coverage_names_read_leaves(tmp_path, tele_env):
    """Acceptance: read_object of 2 of 20 equally-sized leaves →
    whole-snapshot coverage < 0.2, and the heatmap names exactly the
    two read leaves."""
    path = str(tmp_path / "snap")
    state = {
        "m": StateDict(
            **{
                f"w{i:02d}": np.arange(2048, dtype=np.float32) + i
                for i in range(20)
            }
        )
    }
    Snapshot.take(path, state)
    snap = Snapshot(path)
    got = snap.read_object("0/m/w03")
    assert np.array_equal(np.asarray(got), np.asarray(state["m"]["w03"]))
    snap.read_object("0/m/w11")
    hm = compute_heatmap(load_ledger_records(path), _heatmap_metadata(path))
    assert 0 < hm["coverage"] < 0.2
    assert hm["unattributed_bytes"] == 0
    touched = sorted(l["path"] for l in hm["leaves"] if l["bytes_read"])
    assert touched == ["m/w03", "m/w11"]
    per_leaf = {l["path"]: l for l in hm["leaves"]}
    assert per_leaf["m/w03"]["coverage"] == pytest.approx(1.0)
    assert per_leaf["m/w00"]["coverage"] == 0.0
    # The hot ranges name the tiles a serving tier should pin.
    assert {h["path"] for h in hm["hot_ranges"]} == {"m/w03", "m/w11"}


def test_full_restore_coverage_near_one_and_history_fields(
    tmp_path, tele_env
):
    path = str(tmp_path / "snap")
    state = {
        "m": StateDict(
            **{f"w{i}": np.arange(4096, dtype=np.float32) + i for i in range(8)}
        )
    }
    Snapshot.take(path, state)
    dst = {
        "m": StateDict(
            **{f"w{i}": np.zeros(4096, np.float32) for i in range(8)}
        )
    }
    Snapshot(path).restore(dst)
    hm = compute_heatmap(load_ledger_records(path), _heatmap_metadata(path))
    assert hm["coverage"] > 0.99
    assert hm["n_readers"] == 1
    # One full pass: amplification ≈ coverage (every byte read once).
    assert hm["coverage"] <= hm["amplification"] < 1.5
    # The restore history event carries the access_* scalars, and the
    # attributed bytes equal the storage.bytes_read counter exactly.
    ev = [e for e in load_history() if e["kind"] == "restore"][-1]
    assert ev["access_bytes_read"] == ev["bytes"] == hm["bytes_read"]
    assert ev["access_reads"] >= 1
    assert ev["access_working_set_bytes"] == pytest.approx(
        hm["snapshot_bytes"], rel=0.01
    )


# ------------------------------------------------- concurrency soak

_READER_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict
path = sys.argv[1]
dst = {"m": StateDict(**{f"w{i}": np.zeros(4096, np.float32)
                         for i in range(4)})}
Snapshot(path).restore(dst)
assert np.asarray(dst["m"]["w1"])[1] == 2.0
print("OK", flush=True)
"""


def test_many_concurrent_readers_interleave_whole_lines(tmp_path):
    """Satellite: tens of concurrent reader processes sharing one
    telemetry dir — every ledger line parses whole (O_APPEND whole-line
    interleave), and the merged heatmap byte total equals the sum of
    every reader's ``storage.bytes_read`` counter."""
    path = str(tmp_path / "snap")
    tele = str(tmp_path / "tele")
    state = {
        "m": StateDict(
            **{f"w{i}": np.arange(4096, dtype=np.float32) + i for i in range(4)}
        )
    }
    with override_telemetry_dir(tele):
        Snapshot.take(path, state)
    n = 12
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _READER_CHILD, path],
            env={
                **os.environ,
                "TPUSNAP_TELEMETRY_DIR": tele,
                "TPUSNAP_JOB_ID": f"reader-{k}",
            },
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=cwd,
        )
        for k in range(n)
    ]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err
        assert "OK" in out
    with override_telemetry_dir(tele):
        root = access.access_dir(path)
        names = [m for m in os.listdir(root) if m.endswith(".jsonl")]
        assert len(names) == n
        for name in names:
            with open(os.path.join(root, name), "rb") as f:
                lines = [ln for ln in f.read().split(b"\n") if ln.strip()]
            assert lines
            for ln in lines:
                json.loads(ln)  # every interleaved line is whole
        recs = load_ledger_records(path)
        hm = compute_heatmap(recs, _heatmap_metadata(path))
        assert hm["n_readers"] == n
        assert set(hm["readers"]) == {f"reader-{k}" for k in range(n)}
        # Merged bytes == Σ per-reader storage.bytes_read (each child's
        # restore history event records its counter).
        evs = [e for e in load_history() if e["kind"] == "restore"]
        assert len(evs) == n
        assert hm["bytes_read"] == sum(e["bytes"] for e in evs)
        assert hm["bytes_read"] == sum(
            r["bytes_read"] for r in hm["readers"].values()
        )
        # n full passes over one snapshot: cross-reader amplification.
        assert hm["amplification"] == pytest.approx(n * hm["coverage"], rel=0.01)


# ------------------------------------------------------ overhead guard


def test_restore_overhead_with_ledger_within_bound(tmp_path, tele_env):
    """Acceptance: the ≤10% overhead guard holds on restore with the
    access ledger ON (in-memory bucket aggregation; one flush at scope
    exit — no per-read I/O)."""
    per = (16 << 20) // 8 // 4
    state = {
        "m": StateDict(
            **{f"w{i}": np.arange(per, dtype=np.float32) + i for i in range(8)}
        )
    }
    path = str(tmp_path / "snap")
    Snapshot.take(path, state)

    def restore_once(enabled):
        dst = {
            "m": StateDict(
                **{f"w{i}": np.zeros(per, np.float32) for i in range(8)}
            )
        }
        with override_access_ledger(enabled):
            t0 = time.perf_counter()
            Snapshot(path).restore(dst)
            return time.perf_counter() - t0

    restore_once(True)  # warmup
    runs = 5
    disabled = min(restore_once(False) for _ in range(runs))
    enabled = min(restore_once(True) for _ in range(runs))
    assert enabled <= disabled * 1.10 + 0.05, (
        f"access ledger overhead too high: enabled {enabled:.3f}s vs "
        f"disabled {disabled:.3f}s"
    )


# ------------------------------------------------- fleet reader fold


@pytest.fixture
def fleet_env(tmp_path):
    fdir = str(tmp_path / "fleet")
    reset_publisher()
    reset_reader_stats()
    with override_telemetry_dir(str(tmp_path / "tele")), override_fleet_dir(
        fdir
    ), override_job_id("reader-a"):
        yield fdir
    reset_publisher()
    reset_reader_stats()


def test_note_reader_scope_publishes_and_folds(fleet_env):
    note_reader_scope("d1", 1000, 3000, 30)
    note_reader_scope("d1", 1000, 1000, 10)
    recs = read_fleet_records(fleet_env)
    assert len(recs) == 1
    reader = recs[0]["reader"]
    assert reader["bytes_read"] == 4000 and reader["reads"] == 40
    assert reader["snapshots"]["d1"]["scopes"] == 2
    assert reader["snapshots"]["d1"]["snapshot_bytes"] == 1000
    rollup = fold_fleet(recs)
    assert rollup["readers"] == 1
    assert rollup["bytes_read_total"] == 4000
    assert rollup["read_amplification"] == pytest.approx(4.0)
    assert rollup["read_amplification_digest"] == "d1"
    (job,) = rollup["jobs"]
    assert job["reader"] is True and job["bytes_read"] == 4000


def _reader_rec(job, ts, digest, snapshot_bytes, bytes_read):
    return {
        "v": 1,
        "job_id": job,
        "pid": 1,
        "ts": ts,
        "rank": 0,
        "world_size": 1,
        "slo": {
            "rpo_s": 0.0,
            "data_at_risk_bytes": 0,
            "estimated_rto_s": None,
            "last_commit_ts": ts,
            "started_ts": ts,
            "commit_interval_s": None,
            "stream_cadence_s": None,
        },
        "reader": {
            "bytes_read": bytes_read,
            "reads": 1,
            "snapshots": {
                digest: {
                    "snapshot_bytes": snapshot_bytes,
                    "bytes_read": bytes_read,
                    "reads": 1,
                    "scopes": 1,
                }
            },
        },
    }


def test_fold_merges_amplification_across_readers_per_digest():
    """Amplification is a cross-reader, per-digest property: two 1.0x
    readers of one snapshot fold to 2.0x on the serving substrate."""
    t0 = 1_000_000.0
    recs = [
        _reader_rec("a", t0, "d1", 1000, 1000),
        _reader_rec("b", t0, "d1", 1000, 1000),
        _reader_rec("c", t0, "d2", 10_000, 5000),
    ]
    rollup = fold_fleet(recs, now=t0 + 1)
    assert rollup["readers"] == 3
    assert rollup["bytes_read_total"] == 7000
    # Worst digest wins the headline: d1 at 2.0x beats d2 at 0.5x.
    assert rollup["read_amplification"] == pytest.approx(2.0)
    assert rollup["read_amplification_digest"] == "d1"


def test_evaluate_fleet_read_amplification_gate():
    t0 = 1_000_000.0
    rollup = fold_fleet(
        [_reader_rec("a", t0, "d1", 1000, 3000)], now=t0 + 1
    )
    bad = evaluate_fleet(rollup, max_read_amplification=2.0)
    assert bad["verdict"] == "breach"
    row = next(
        c for c in bad["checks"] if c["check"] == "read_amplification"
    )
    assert row["breach"] and row["job"] == "d1"
    ok = evaluate_fleet(rollup, max_read_amplification=5.0)
    assert ok["verdict"] == "healthy"
    # No readers at all: the check is SKIPPED, not breached — absence
    # of readers is not a serving problem.
    no_readers = fold_fleet(
        [
            {
                "v": 1,
                "job_id": "w",
                "pid": 1,
                "ts": t0,
                "rank": 0,
                "world_size": 1,
                "slo": {
                    "rpo_s": 0.0,
                    "data_at_risk_bytes": 0,
                    "estimated_rto_s": None,
                    "last_commit_ts": t0,
                    "started_ts": t0,
                    "commit_interval_s": None,
                    "stream_cadence_s": None,
                },
            }
        ],
        now=t0 + 1,
    )
    rep = evaluate_fleet(no_readers, max_read_amplification=0.1)
    assert rep["verdict"] == "healthy"
    assert not any(
        c["check"] == "read_amplification" for c in rep["checks"]
    )


def test_fleet_prom_reader_families():
    t0 = 1_000_000.0
    rollup = fold_fleet(
        [_reader_rec("a", t0, "d1", 1000, 3000)], now=t0 + 1
    )
    text = render_fleet_prom(rollup)
    families = parse_prometheus_textfile(text)
    readers = families["tpusnap_fleet_readers"]["samples"]
    assert next(iter(readers.values())) == 1.0
    amp = families["tpusnap_fleet_read_amplification"]["samples"]
    (key, val) = next(iter(amp.items()))
    assert 'digest="d1"' in key and val == pytest.approx(3.0)
    # Without readers the amplification family is absent; the reader
    # count gauge stays (0 is a fact, not a gap).
    empty = fold_fleet(
        [
            {
                "v": 1,
                "job_id": "w",
                "pid": 1,
                "ts": t0,
                "rank": 0,
                "world_size": 1,
                "slo": {
                    "rpo_s": 0.0,
                    "data_at_risk_bytes": 0,
                    "estimated_rto_s": None,
                    "last_commit_ts": t0,
                    "started_ts": t0,
                    "commit_interval_s": None,
                    "stream_cadence_s": None,
                },
            }
        ],
        now=t0 + 1,
    )
    fam2 = parse_prometheus_textfile(render_fleet_prom(empty))
    assert (
        next(iter(fam2["tpusnap_fleet_readers"]["samples"].values())) == 0.0
    )
    assert "tpusnap_fleet_read_amplification" not in fam2


# --------------------------------------------- analyze + tune advice


def test_analyze_partial_access_finding():
    hm = {
        "coverage": 0.1,
        "bytes_read": 4096,
        "n_readers": 2,
        "hot_ranges": [{"path": "m/w1", "range": [0, 128]}],
    }
    (f,) = access_findings(hm, Thresholds())
    assert f.severity == "info" and f.kind == "partial_access"
    assert "10%" in f.message and "m/w1[0:128)" in f.message
    assert "read_object" in f.message
    # High coverage, or a heatmap with no attributed reads: no finding.
    assert access_findings({**hm, "coverage": 0.9}, Thresholds()) == []
    assert access_findings({**hm, "bytes_read": 0}, Thresholds()) == []


def _restore_events(n, **extra):
    return [
        {
            "kind": "restore",
            "plugin": "FSStoragePlugin",
            "world_size": 1,
            "bytes": GiB,
            "wall_s": 2.0,
            **extra,
        }
        for _ in range(n)
    ]


def test_tune_sizes_restore_budget_to_access_working_set(monkeypatch):
    """Partial-reader history (working set ≪ payload) → the planner
    proposes a restore budget of 2x the hot working set."""
    monkeypatch.delenv(
        "TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES", raising=False
    )
    events = _restore_events(
        5, access_working_set_bytes=64 * MiB, access_bytes_read=80 * MiB
    )
    plan = build_plan(events, "restore", ceilings={}, codec_gbps=0.0)
    assert plan.ok
    envs = {k.env: k.value for k in plan.knobs}
    assert envs["TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES"] == str(128 * MiB)
    # Full-restore history (working set ≈ payload): rule stays quiet.
    full = _restore_events(
        5, access_working_set_bytes=GiB, access_bytes_read=GiB
    )
    plan2 = build_plan(full, "restore", ceilings={}, codec_gbps=0.0)
    assert "TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES" not in {
        k.env for k in plan2.knobs
    }
    # Re-reading history (bytes_read ≫ working set) means the reads
    # revisit tiles — a tight budget would thrash; rule stays quiet.
    rereads = _restore_events(
        5, access_working_set_bytes=64 * MiB, access_bytes_read=512 * MiB
    )
    plan3 = build_plan(rereads, "restore", ceilings={}, codec_gbps=0.0)
    assert "TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES" not in {
        k.env for k in plan3.knobs
    }


# ------------------------------------------------ gc cold-first order


def test_gc_evict_local_deletes_cold_blobs_first(tmp_path, monkeypatch):
    """``gc --evict-local`` evicts never-read blobs before the fleet's
    hot tiles: an interrupted eviction leaves the popular working set
    on the fast tier."""
    # The explicit drain below must be the ONLY drain: the take's
    # background uploader would race it on the upload journal.
    monkeypatch.setenv("TPUSNAP_TIER_DRAIN", "0")
    cache = os.path.join(str(tmp_path), "cache")
    remote_root = os.path.join(str(tmp_path), "remote")
    url = f"tier+local={cache}+remote=fs://{remote_root}/snap"
    state = {
        "m": StateDict(
            **{
                f"w{i}": np.arange(4096, dtype=np.float32) + i
                for i in range(6)
            }
        )
    }
    with override_telemetry_dir(
        str(tmp_path / "tele")
    ), knobs.override_batching_disabled(True):
        Snapshot.take(url, state)
        assert drain_snapshot(url).state == "durable"
        snap = Snapshot(url)
        snap.read_object("0/m/w4")
        for _ in range(3):
            snap.read_object("0/m/w2")
        local_dir = parse_tier_url(url).local_dir
        # Ledgers recorded via the tier-URL spelling must be findable
        # from the local dir (digest normalization).
        counts = location_read_counts(load_ledger_records(local_dir))
        assert counts and len(counts) == 2
        warm_loc = min(counts, key=counts.get)  # w4: 1 read
        hot_loc = max(counts, key=counts.get)  # w2: 3 reads
        order = []
        orig = StoragePlugin.sync_delete

        def recording_delete(self, p, loop):
            order.append(p)
            return orig(self, p, loop)

        monkeypatch.setattr(StoragePlugin, "sync_delete", recording_delete)
        report = gc_snapshot(url, dry_run=False, evict_local=True)
        assert not report.errors
        payload = [p for p in order if p in report.reclaimed]
        assert hot_loc in payload and warm_loc in payload
        # Cold (never-read) blobs go first; warm before hot; the
        # hottest tile is the LAST payload blob to leave the cache.
        assert payload[-1] == hot_loc
        assert payload[-2] == warm_loc


# ---------------------------------------------------- heatmap CLI leg


def test_heatmap_cli_exit_contract(tmp_path, tele_env, capsys):
    path = str(tmp_path / "snap")
    state = {
        "m": StateDict(
            **{f"w{i}": np.arange(4096, dtype=np.float32) + i for i in range(4)}
        )
    }
    Snapshot.take(path, state)
    # No ledgers yet: exit 3 (no data, the slo/history stance).
    assert main(["heatmap", path]) == 3
    capsys.readouterr()
    Snapshot(path).read_object("0/m/w0")
    assert main(["heatmap", path]) == 0
    out = capsys.readouterr().out
    assert "coverage" in out and "m/w0" in out
    assert main(["heatmap", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_readers"] == 1
    assert 0 < doc["coverage"] < 1
    assert "breach" not in doc  # only stamped when a threshold is set
    assert (
        main(["heatmap", path, "--json", "--max-amplification", "5"]) == 0
    )
    doc = json.loads(capsys.readouterr().out)
    assert doc["breach"] is False and doc["max_amplification"] == 5.0
    # Gate: amplification over budget → exit 2; within → 0.
    assert (
        main(["heatmap", path, "--check", "--max-amplification", "0.01"])
        == 2
    )
    capsys.readouterr()
    assert (
        main(["heatmap", path, "--check", "--max-amplification", "5"]) == 0
    )
    capsys.readouterr()
