"""fs plugin + registry + native helper tests (reference exercises its fs
plugin implicitly via Snapshot tests and tmp_path)."""

import asyncio
import os

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict
from tpusnap.io_types import ReadIO, WriteIO
from tpusnap.knobs import override_slab_size_threshold_bytes
from tpusnap.storage_plugin import url_to_storage_plugin
from tpusnap.storage_plugins.fs import FSStoragePlugin


def _run(coro):
    return asyncio.run(coro)


def test_registry_schemes(tmp_path):
    from tpusnap.retry import RetryingStoragePlugin
    from tpusnap.storage_plugin import InstrumentedStoragePlugin

    # Built-in plugins come wrapped retry(instrument(raw)): whole-op
    # retry outermost, the histogram instrumentation inside it (so each
    # attempt is one latency sample, without backoff sleeps).
    p = url_to_storage_plugin(str(tmp_path))
    assert isinstance(p, RetryingStoragePlugin)
    assert isinstance(p.inner, InstrumentedStoragePlugin)
    assert isinstance(p.inner.inner, FSStoragePlugin)
    assert p.inner.label == "FSStoragePlugin"
    p = url_to_storage_plugin(f"fs://{tmp_path}")
    assert isinstance(p.inner.inner, FSStoragePlugin)
    # storage_options={"retry": False} drops retry, keeps instrumentation.
    p = url_to_storage_plugin(str(tmp_path), {"retry": False})
    assert isinstance(p, InstrumentedStoragePlugin)
    assert isinstance(p.inner, FSStoragePlugin)
    p = url_to_storage_plugin(f"fsspec+memory://snap")
    from tpusnap.storage_plugins.fsspec import FsspecStoragePlugin

    assert isinstance(p.inner.inner, FsspecStoragePlugin)
    with pytest.raises(RuntimeError, match="Unsupported storage scheme"):
        url_to_storage_plugin("bogus://x")
    # S3 construction succeeds without aiobotocore (deferred import so a
    # stub client can be injected); first real use raises. Unknown
    # attributes pass through the instrumentation wrapper.
    s3 = url_to_storage_plugin("s3://bucket/prefix")
    with pytest.raises(RuntimeError, match="aiobotocore"):
        _run(s3.inner._get_client())


def test_registry_chaos_scheme(tmp_path):
    """chaos+<scheme>:// composes Retrying(Instrumented(FaultInjection(
    raw))) so injected faults exercise the production retry path AND
    injected latency lands in the histograms as the fat tail it is."""
    from tpusnap.faults import FaultInjectionStoragePlugin, FaultPlan
    from tpusnap.retry import RetryingStoragePlugin
    from tpusnap.storage_plugin import InstrumentedStoragePlugin

    def _unwrap(plugin):
        assert isinstance(plugin, RetryingStoragePlugin)
        assert isinstance(plugin.inner, InstrumentedStoragePlugin)
        return plugin.inner.inner

    p = url_to_storage_plugin(f"chaos+fs://{tmp_path}")
    fault = _unwrap(p)
    assert isinstance(fault, FaultInjectionStoragePlugin)
    assert isinstance(fault.inner, FSStoragePlugin)
    # ...and the instrumentation labels by the RAW backend class.
    assert p.inner.label == "FSStoragePlugin"
    # default plan: ≥1 transient error per distinct op. (Attribute
    # passthrough: p.inner.plan delegates through the instrumentation.)
    assert fault.plan.transient_per_op == 1
    assert p.inner.plan.transient_per_op == 1
    # explicit plans ride storage_options (FaultPlan, spec str, or dict)
    p = url_to_storage_plugin(
        f"chaos+fs://{tmp_path}",
        {"fault_plan": FaultPlan(seed=7, transient_every=3, torn_writes=True)},
    )
    assert _unwrap(p).plan.seed == 7 and _unwrap(p).plan.torn_writes
    p = url_to_storage_plugin(
        f"chaos+fs://{tmp_path}",
        {"fault_plan": "seed=2,transient_per_op=2,latency_ms=1"},
    )
    assert _unwrap(p).plan.seed == 2
    assert _unwrap(p).plan.transient_per_op == 2
    assert abs(_unwrap(p).plan.latency_sec - 0.001) < 1e-9
    # chaos over the generic fsspec bridge
    p = url_to_storage_plugin("chaos+fsspec+memory://snapchaos")
    from tpusnap.storage_plugins.fsspec import FsspecStoragePlugin

    assert isinstance(_unwrap(p).inner, FsspecStoragePlugin)


def test_fs_write_read_roundtrip(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))

    async def go():
        data = os.urandom(1 << 16)
        await plugin.write(WriteIO(path="a/b/c", buf=memoryview(data)))
        read_io = ReadIO(path="a/b/c")
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == data
        # ranged read
        read_io = ReadIO(path="a/b/c", byte_range=(100, 356))
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == data[100:356]
        await plugin.delete("a/b/c")
        assert not (tmp_path / "a" / "b" / "c").exists()
        await plugin.close()

    _run(go())


def test_fs_large_write_native_path(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    data = os.urandom(5 * 1024 * 1024)  # over the native threshold

    async def go():
        await plugin.write(WriteIO(path="big", buf=memoryview(data)))
        read_io = ReadIO(path="big")
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == data
        await plugin.close()

    _run(go())


def test_fs_direct_io_roundtrip(tmp_path):
    """O_DIRECT writes must be bit-exact for unaligned sizes (the aligned
    bulk goes through the direct fd, the tail through a buffered one) and
    the knob must force the buffered path."""
    from tpusnap import _native
    from tpusnap.knobs import override_direct_io_disabled

    for nbytes in (4 * 1024 * 1024, 8 * 1024 * 1024 + 4096, 9 * 1024 * 1024 + 7):
        data = os.urandom(nbytes)
        for disabled in (False, True):
            with override_direct_io_disabled(disabled):
                path = str(tmp_path / f"d{nbytes}_{disabled}")
                _native.write_file(path, memoryview(data))
                with open(path, "rb") as f:
                    assert f.read() == data
                # ranged reads: aligned, misaligned head/tail, past-EOF
                for off, n in ((0, nbytes), (4096, 5 * 1024 * 1024),
                               (1234, 4 * 1024 * 1024 + 77),
                               (nbytes - 100, 500),
                               # large request starting in the final
                               # partial block: empty aligned window
                               (nbytes - 3, 4 * 1024 * 1024)):
                    out = bytearray(n)
                    got = _native.read_range(path, off, n, out)
                    assert bytes(out[:got]) == data[off:off + n]


def test_fs_concurrent_writes(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))

    async def go():
        blobs = {f"obj{i}": os.urandom(10_000) for i in range(32)}
        await asyncio.gather(
            *(plugin.write(WriteIO(path=k, buf=v)) for k, v in blobs.items())
        )
        for k, v in blobs.items():
            read_io = ReadIO(path=k)
            await plugin.read(read_io)
            assert read_io.buf.getvalue() == v
        await plugin.close()

    _run(go())


def test_fsspec_memory_roundtrip():
    plugin = url_to_storage_plugin("fsspec+memory://snaptest")

    async def go():
        await plugin.write(WriteIO(path="x/y", buf=b"hello"))
        read_io = ReadIO(path="x/y")
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == b"hello"
        read_io = ReadIO(path="x/y", byte_range=(1, 4))
        await plugin.read(read_io)
        assert read_io.buf.getvalue() == b"ell"
        await plugin.delete("x/y")
        await plugin.close()

    _run(go())


def test_sync_shims(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    plugin.sync_write(WriteIO(path="s", buf=b"sync"))
    read_io = ReadIO(path="s")
    plugin.sync_read(read_io)
    assert read_io.buf.getvalue() == b"sync"
    plugin.sync_close()


class TestNative:
    def test_write_and_read_range(self, tmp_path):
        from tpusnap import _native

        data = os.urandom(1 << 20)
        path = str(tmp_path / "n.bin")
        _native.write_file(path, memoryview(data))
        assert open(path, "rb").read() == data
        out = bytearray(1000)
        got = _native.read_range(path, 500, 1000, out)
        assert got == 1000 and bytes(out) == data[500:1500]
        # EOF-short read
        out = bytearray(100)
        got = _native.read_range(path, len(data) - 10, 100, out)
        assert got == 10 and bytes(out[:10]) == data[-10:]

    def test_memcpy(self):
        from tpusnap import _native

        src = os.urandom(3 << 20)
        dst = bytearray(len(src))
        _native.memcpy(dst, src)
        assert bytes(dst) == src
        with pytest.raises(ValueError):
            _native.memcpy(bytearray(5), b"123")

    def test_crc32c_known_vector(self):
        from tpusnap import _native

        if not _native.available():
            pytest.skip("native unavailable")
        # RFC 3720 test vector: crc32c of 32 zero bytes == 0x8a9136aa
        assert _native.crc32c(bytes(32)) == 0x8A9136AA
        assert _native.checksum_algorithm() == "crc32c"

    def test_disabled_fallback(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUSNAP_DISABLE_NATIVE", "1")
        # force a fresh load decision in a subprocess to honor the env var
        import subprocess
        import sys

        code = (
            "import os; os.environ['TPUSNAP_DISABLE_NATIVE']='1';"
            "from tpusnap import _native;"
            f"p=r'{tmp_path}/f.bin';"
            "_native.write_file(p, b'abc');"
            "assert open(p,'rb').read()==b'abc';"
            "assert not _native.available();"
            "print('fallback-ok')"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, cwd="/root/repo"
        )
        assert "fallback-ok" in out.stdout, out.stderr


def test_register_storage_plugin_runtime(tmp_path):
    """Runtime-registered schemes take effect without packaging
    (complements the entry-point group)."""
    from tpusnap.storage_plugin import (
        register_storage_plugin,
        unregister_storage_plugin,
        url_to_storage_plugin,
    )
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    calls = {}

    def factory(path, storage_options):
        calls["path"] = path
        return FSStoragePlugin(root=str(tmp_path / path), storage_options=storage_options)

    register_storage_plugin("memtest", factory)
    try:
        plugin = url_to_storage_plugin("memtest://sub/dir")
        assert isinstance(plugin, FSStoragePlugin)
        assert calls["path"] == "sub/dir"
    finally:
        unregister_storage_plugin("memtest")
    with pytest.raises(RuntimeError):
        url_to_storage_plugin("memtest://sub/dir")


class TestReadInto:
    """In-place reads: bytes land directly in the consumer-provided
    destination with the checksum fused into the native copy-out."""

    def test_read_range_into_correctness(self, tmp_path):
        from tpusnap import _native

        rng = np.random.default_rng(3)
        n = 9 * 1024 * 1024 + 1234
        data = rng.integers(0, 255, n, dtype=np.uint8).tobytes()
        path = str(tmp_path / "blob")
        open(path, "wb").write(data)
        cases = [
            (0, n),                       # whole file
            (0, 5 * 1024 * 1024 + 17),    # aligned start, odd length
            (1, n - 1),                   # misaligned head
            (4096, 6 * 1024 * 1024),      # aligned window
            (777, 8 * 1024 * 1024 + 5),   # misaligned head + tail
            (n - 100, 100),               # small tail
            (n - 100, 500),               # EOF-short
            (0, 1000),                    # small (buffered path)
        ]
        for off, ln in cases:
            out = np.empty(ln, dtype=np.uint8)
            got, crc, algo = _native.read_range_into(
                path, off, ln, out, want_crc=True
            )
            expect = data[off : off + ln]
            assert got == len(expect), (off, ln)
            assert out[:got].tobytes() == expect, (off, ln)
            assert crc == _native.crc32c(expect), (off, ln)
        # aligned destination takes the zero-copy direct path
        out = _native.aligned_empty(8 * 1024 * 1024)
        got, crc, algo = _native.read_range_into(
            path, 0, 8 * 1024 * 1024, out, want_crc=True
        )
        assert got == 8 * 1024 * 1024
        assert bytes(out) == data[:got] and crc == _native.crc32c(data[:got])
        # want_crc=False reports no checksum
        got, crc, algo = _native.read_range_into(
            path, 0, 4 * 1024 * 1024, np.empty(4 * 1024 * 1024, np.uint8)
        )
        assert got == 4 * 1024 * 1024 and crc is None

    def test_fs_plugin_honors_into(self, tmp_path):
        plugin = FSStoragePlugin(root=str(tmp_path))
        data = os.urandom(5 * 1024 * 1024)

        async def go():
            await plugin.write(WriteIO(path="b", buf=data))
            dst = np.empty(len(data), dtype=np.uint8)
            read_io = ReadIO(path="b", into=memoryview(dst), want_crc=True)
            await plugin.read(read_io)
            assert read_io.in_place
            assert dst.tobytes() == data
            from tpusnap import _native

            if _native.available():
                assert read_io.crc32c == _native.crc32c(data)
                assert read_io.crc_algo == "crc32c"
            # the generic buf view still works for fallback consumers
            assert bytes(read_io.buf.getbuffer()) == data
            await plugin.close()

        _run(go())

    def test_restore_lands_in_place(self, tmp_path):
        """A numpy restore target with matching dtype/shape receives the
        bytes directly — the future resolves to the SAME array object."""
        arr = np.random.default_rng(5).standard_normal(500_000).astype(np.float32)
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr.copy())})
        target_arr = np.zeros_like(arr)
        target = {"m": StateDict(w=target_arr)}
        Snapshot(str(tmp_path / "s")).restore(target)
        assert target["m"]["w"] is target_arr
        assert np.array_equal(target_arr, arr)

    def test_in_place_short_read_fails_loudly(self, tmp_path):
        """A truncated blob must raise, not silently leave a partial
        restore in the target — even with checksum verification off
        (the truncated size disqualifies the in-place path, and the
        generic deserialize raises on the size mismatch)."""
        from tpusnap.knobs import override_checksum_disabled

        arr = np.arange(300_000, dtype=np.float32)
        with override_slab_size_threshold_bytes(1024):
            Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=arr)})
        blob = str(tmp_path / "s" / "0" / "m" / "w")
        assert os.path.isfile(blob)
        with open(blob, "r+b") as f:
            f.truncate(arr.nbytes // 2)
        for checksum_off in (False, True):
            with override_checksum_disabled(checksum_off):
                target = {"m": StateDict(w=np.zeros_like(arr))}
                with pytest.raises((IOError, ValueError)):
                    Snapshot(str(tmp_path / "s")).restore(target)


class TestAbortPath:
    """A failed read must surface the ORIGINAL error, leave no stranded
    tasks on the (cached, reused) event loop, and leave no plugin
    thread still writing into caller-owned memory."""

    def test_failed_restore_surfaces_original_error_and_loop_reusable(
        self, tmp_path
    ):
        from tpusnap._native import ChecksumError

        arrs = {
            f"w{i}": np.arange(400_000, dtype=np.float32) + i for i in range(6)
        }
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(**arrs)})
        snap = Snapshot(str(tmp_path / "s"))
        entry = snap.get_manifest()["0/m/w2"]
        blob = str(tmp_path / "s" / "0" / "m" / "w2")
        if not os.path.isfile(blob):
            import glob as _glob

            blob = _glob.glob(str(tmp_path / "s" / "batched" / "*"))[0]
        off = (entry.byte_range[0] if entry.byte_range else 0) + 16
        with open(blob, "r+b") as fh:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ 0xFF]))

        # Repeated fail -> reuse cycles on the same handle: the original
        # ChecksumError (not a secondary abort artifact) must surface
        # every time, and clean blobs must read correctly afterwards.
        for _ in range(3):
            with pytest.raises(ChecksumError, match="w2"):
                snap.restore(
                    {
                        "m": StateDict(
                            **{k: np.zeros_like(v) for k, v in arrs.items()}
                        )
                    }
                )
            out = snap.read_object("0/m/w5")
            np.testing.assert_array_equal(out, arrs["w5"])
        # After the abort drain, the plugin reports no in-flight work.
        _, storage = snap._resources()
        storage.drain_in_flight()
        assert not storage.__dict__.get("_tracked_inflight")
        snap.close()

    def test_run_on_loop_drains_stranded_task(self):
        """A BaseException escaping run_until_complete must not leave
        the top-level task pending on the loop."""
        import asyncio

        from tpusnap.io_types import run_on_loop

        loop = asyncio.new_event_loop()
        state = {"cancelled": False}

        async def work():
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                state["cancelled"] = True
                raise

        task = loop.create_task(work())

        # Simulate an interrupt escaping the loop machinery: stop the
        # loop via a KeyboardInterrupt raised from a scheduled callback.
        def boom():
            raise KeyboardInterrupt

        loop.call_later(0.05, boom)
        with pytest.raises(KeyboardInterrupt):
            run_on_loop(loop, task)
        assert task.done() and state["cancelled"]
        # The loop is clean: a fresh coroutine runs unobstructed.
        assert loop.run_until_complete(asyncio.sleep(0, result=42)) == 42
        loop.close()


def test_write_atomic_durable_flag(tmp_path):
    """durable=True fsyncs (file + parent dir) and still lands the same
    bytes; the take commit honors TPUSNAP_DURABLE_COMMIT."""
    import asyncio
    import os

    from tpusnap.io_types import WriteIO
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    loop = asyncio.new_event_loop()
    plugin = FSStoragePlugin(str(tmp_path))
    fsyncs = []
    real_fsync = os.fsync
    try:
        plugin.sync_write_atomic(
            WriteIO(path="meta", buf=b"payload-1"), loop, durable=False
        )
        assert (tmp_path / "meta").read_bytes() == b"payload-1"
        import unittest.mock as mock

        with mock.patch("os.fsync", side_effect=lambda fd: (fsyncs.append(fd), real_fsync(fd))):
            plugin.sync_write_atomic(
                WriteIO(path="meta", buf=b"payload-2"), loop, durable=True
            )
        assert (tmp_path / "meta").read_bytes() == b"payload-2"
        assert len(fsyncs) == 2  # temp file + parent directory
    finally:
        plugin.sync_close(loop)
        loop.close()


def test_durable_commit_knob_round_trip(tmp_path, monkeypatch):
    import numpy as np

    from tpusnap import Snapshot, StateDict

    monkeypatch.setenv("TPUSNAP_DURABLE_COMMIT", "1")
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(w=np.arange(32, dtype=np.float32))})
    target = {"app": StateDict(w=np.zeros(32, np.float32))}
    Snapshot(path).restore(target)
    assert np.array_equal(target["app"]["w"], np.arange(32, dtype=np.float32))
