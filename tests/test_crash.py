"""Crash consistency and scale-shape regression tests.

The two-phase commit's real-world guarantee: a take killed with SIGKILL
at any point (no Python cleanup, no atexit) leaves NO
``.snapshot_metadata`` — the partial snapshot is invisible — and the
same path remains usable for a subsequent take. The reference asserts
this only for in-process exceptions (tests/test_async_take.py); a hard
kill is the stronger claim.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, verify_snapshot

_TAKE_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

path = sys.argv[1]
state = {
    f"w{i}": np.random.default_rng(i).standard_normal((512, 1024)).astype(np.float32)
    for i in range(24)
}  # ~48 MB -> many distinct blob files with batching off
os.environ["TPUSNAP_DISABLE_BATCHING"] = "1"
print("READY", flush=True)
Snapshot.take(path, {"app": StateDict(**state)})
print("DONE", flush=True)
"""


def test_sigkill_mid_take_leaves_no_metadata(tmp_path):
    path = str(tmp_path / "snap")
    env = dict(os.environ, JAX_PLATFORMS="cpu", TPUSNAP_DISABLE_BATCHING="1")
    proc = subprocess.Popen(
        [sys.executable, "-c", _TAKE_CHILD, path],
        env=env,
        stdout=subprocess.PIPE,
        text=True,
    )
    try:
        # Wait for blobs to start appearing, then kill mid-write: blob
        # files exist, metadata (written last, after the barrier) not.
        deadline = time.monotonic() + 120
        killed = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break  # finished before we saw a blob (too fast)
            if os.path.isdir(path) and any(
                f != ".snapshot_metadata"
                for _, _, fs in os.walk(path)
                for f in fs
            ):
                proc.send_signal(signal.SIGKILL)
                killed = True
                break
            time.sleep(0.002)
        proc.wait(timeout=60)
        if not killed:
            pytest.skip("take finished before any blob appeared")
        if proc.stdout is not None and "DONE" in (proc.stdout.read() or ""):
            # TOCTOU: the child finished the commit between the blob scan
            # and signal delivery — nothing mid-flight to assert about.
            pytest.skip("take completed before SIGKILL landed")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # The invariant: no metadata -> the partial snapshot is invisible.
    assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))
    with pytest.raises(RuntimeError, match="not a snapshot"):
        Snapshot(path).metadata

    # The same path is reusable; the fresh take overwrites the debris
    # and scrubs clean.
    fresh = StateDict(x=np.arange(4096, dtype=np.float32))
    Snapshot.take(path, {"app": fresh})
    report = verify_snapshot(path)
    assert report.clean
    target = {"app": StateDict(x=np.zeros(4096, np.float32))}
    Snapshot(path).restore(target)
    assert np.array_equal(target["app"]["x"], fresh["x"])


def test_many_leaf_state_stays_compact(tmp_path):
    """10k small leaves (the optimizer-state shape) must slab-batch into
    a handful of files and round-trip; a regression to per-leaf files
    would blow up metadata and storage-op counts."""
    rng = np.random.default_rng(0)
    state = {
        f"p{i}": rng.standard_normal(64).astype(np.float32)
        for i in range(10_000)
    }
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"app": StateDict(**state)})
    n_files = sum(
        len(fs)
        for d, _, fs in os.walk(path)
        if ".tpusnap" not in d.split(os.sep)
    )
    assert n_files <= 8, f"{n_files} files for 10k leaves — batching broken?"
    target = {
        "app": StateDict(**{k: np.zeros(64, np.float32) for k in state})
    }
    Snapshot(path).restore(target)
    for k in ("p0", "p5000", "p9999"):
        assert np.array_equal(target["app"][k], state[k]), k
    assert verify_snapshot(path).clean
