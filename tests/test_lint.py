"""The AST invariant checker (``tpusnap lint``): per-rule unit matrix on
synthetic snippets (positive / negative / waived), the whole-package
zero-findings gate tier-1 rides on, and the CLI exit-code contract —
exit 0 on the clean tree, exit 2 when a violation of each shipped rule
is seeded into a temp copy of the package."""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from tpusnap.devtools.lint import (
    parse_waivers,
    render_table,
    run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, files, select=None, api_md=None):
    """Build a throwaway package tree from ``files`` (relpath → source)
    and lint it."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    if api_md is not None:
        docs = tmp_path / "docs"
        docs.mkdir(exist_ok=True)
        (docs / "api.md").write_text(textwrap.dedent(api_md))
    return run_lint(package_root=str(pkg), select=select)


def _rules_of(result):
    return [f.rule for f in result.findings]


# ------------------------------------------------------------- framework


def test_parse_waivers_same_line_and_comma_list():
    w = parse_waivers(
        "x = 1  # tpusnap: waive=TPS001 reason text\n"
        "y = 2  # tpusnap: waive=TPS003,TPS004\n"
        "z = 3\n"
    )
    assert w == {1: {"TPS001"}, 2: {"TPS003", "TPS004"}}


def test_parse_waivers_comment_above_applies_to_next_code_line():
    w = parse_waivers(
        "a = 1\n"
        "# tpusnap: waive=TPS004 why this swallow is fine\n"
        "# (continued explanation)\n"
        "pass_line = 2\n"
    )
    assert w == {4: {"TPS004"}}


def test_parse_waivers_blank_line_clears_pending():
    """A stale waive comment stranded by a refactor (blank line between
    it and the next code) must NOT suppress findings further down."""
    w = parse_waivers(
        "# tpusnap: waive=TPS004 this statement was deleted\n"
        "\n"
        "x = 1\n"
    )
    assert w == {}


def test_unknown_rule_select_raises(tmp_path):
    with pytest.raises(RuntimeError, match="TPS999"):
        _lint(tmp_path, {"a.py": "x = 1\n"}, select=["TPS999"])


def test_unparseable_file_is_a_finding(tmp_path):
    res = _lint(tmp_path, {"bad.py": "def broken(:\n"}, select=["TPS001"])
    assert _rules_of(res) == ["PARSE"]


# ---------------------------------------------------------------- TPS001


TPS001_CASES = [
    'import os\nX = os.environ.get("TPUSNAP_FOO")\n',
    'import os\nX = os.environ["TPUSNAP_FOO"]\n',
    'import os\nX = os.getenv("TPUSNAP_FOO")\n',
    'import os\nX = "TPUSNAP_FOO" in os.environ\n',
    'from os import environ as env\nX = env.get("TPUSNAP_FOO")\n',
    'from os import getenv\nX = getenv("TPUSNAP_FOO")\n',
    'import os as _o\n_o.environ["TPUSNAP_FOO"] = "1"\n',
]


@pytest.mark.parametrize("src", TPS001_CASES)
def test_tps001_positive(tmp_path, src):
    res = _lint(tmp_path, {"mod.py": src}, select=["TPS001"])
    assert _rules_of(res) == ["TPS001"], render_table(res)


def test_tps001_negative(tmp_path):
    res = _lint(
        tmp_path,
        {
            # knobs.py is the blessed accessor
            "knobs.py": 'import os\nX = os.environ.get("TPUSNAP_FOO")\n',
            # non-TPUSNAP keys are out of scope
            "mod.py": 'import os\nX = os.environ.get("OTHER_VAR")\n',
        },
        select=["TPS001"],
    )
    assert res.findings == []


def test_tps001_waived(tmp_path):
    res = _lint(
        tmp_path,
        {
            "mod.py": (
                "import os\n"
                'X = os.environ["TPUSNAP_T"]  # tpusnap: waive=TPS001 why\n'
            )
        },
        select=["TPS001"],
    )
    assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------- TPS002


@pytest.mark.parametrize(
    "src",
    [
        "import time\nx = time.time()\n",
        "import time as t\nx = t.time()\n",
        "from time import time\nx = time()\n",
        "from time import time as now\nx = now()\n",
    ],
)
def test_tps002_positive(tmp_path, src):
    res = _lint(tmp_path, {"telemetry.py": src}, select=["TPS002"])
    assert _rules_of(res) == ["TPS002"], render_table(res)


def test_tps002_negative(tmp_path):
    res = _lint(
        tmp_path,
        {
            # the seam: a bare reference, not a call
            "progress.py": "import time\n_wall = time.time\n",
            # monotonic is the point
            "history.py": "import time\nx = time.monotonic()\n",
            # out-of-scope module may use wall clocks
            "other.py": "import time\nx = time.time()\n",
        },
        select=["TPS002"],
    )
    assert res.findings == []


def test_tps002_waived(tmp_path):
    res = _lint(
        tmp_path,
        {
            "history.py": (
                "import time\n"
                "x = time.time()  # tpusnap: waive=TPS002 event timestamp\n"
            )
        },
        select=["TPS002"],
    )
    assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------- TPS003


def test_tps003_positive(tmp_path):
    needle = ".tpusnap" + "/"
    res = _lint(
        tmp_path,
        {
            "mod.py": f'P = "{needle}journal"\n',
            "fstr.py": (
                "def p(r):\n"
                f'    return f"{needle}probe/rank_{{r}}.bin"\n'
            ),
        },
        select=["TPS003"],
    )
    assert sorted(_rules_of(res)) == ["TPS003", "TPS003"], render_table(res)


def test_tps003_negative(tmp_path):
    needle = ".tpusnap" + "/"
    res = _lint(
        tmp_path,
        {
            # the canonical definition site
            "io_types.py": f'SIDECAR_PREFIX = "{needle}"\n',
            # docstrings describe the layout; they don't implement it
            "mod.py": f'"""Sidecars live under {needle}."""\nX = 1\n',
        },
        select=["TPS003"],
    )
    assert res.findings == []


def test_tps003_waived(tmp_path):
    needle = ".tpusnap" + "/"
    res = _lint(
        tmp_path,
        {"mod.py": f'P = "{needle}x"  # tpusnap: waive=TPS003 test fixture\n'},
        select=["TPS003"],
    )
    assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------- TPS004


@pytest.mark.parametrize(
    "handler", ["except Exception:", "except BaseException:", "except:"]
)
def test_tps004_positive(tmp_path, handler):
    src = f"def f():\n    try:\n        g()\n    {handler}\n        pass\n"
    res = _lint(tmp_path, {"comm.py": src}, select=["TPS004"])
    assert _rules_of(res) == ["TPS004"], render_table(res)


def test_tps004_negative(tmp_path):
    res = _lint(
        tmp_path,
        {
            # a log call makes the swallow deliberate and visible
            "dist_store.py": (
                "def f():\n"
                "    try:\n"
                "        g()\n"
                "    except Exception:\n"
                "        logger.debug('x', exc_info=True)\n"
            ),
            # narrow exception types are deliberate control flow
            "lifecycle.py": (
                "def f():\n"
                "    try:\n"
                "        g()\n"
                "    except ValueError:\n"
                "        pass\n"
            ),
            # out-of-scope modules are not crash-safety surface
            "other.py": (
                "def f():\n"
                "    try:\n"
                "        g()\n"
                "    except Exception:\n"
                "        pass\n"
            ),
        },
        select=["TPS004"],
    )
    assert res.findings == []


def test_tps004_waived_same_line_and_comment_above(tmp_path):
    res = _lint(
        tmp_path,
        {
            "comm.py": (
                "def f():\n"
                "    try:\n"
                "        g()\n"
                "    except Exception:\n"
                "        pass  # tpusnap: waive=TPS004 reason\n"
            ),
            "faults.py": (
                "def f():\n"
                "    try:\n"
                "        g()\n"
                "    except Exception:\n"
                "        # tpusnap: waive=TPS004 injected-fault path\n"
                "        # re-raises below either way\n"
                "        pass\n"
            ),
        },
        select=["TPS004"],
    )
    assert res.findings == [] and len(res.waived) == 2


# ---------------------------------------------------------------- TPS005


@pytest.mark.parametrize(
    "src",
    [
        "import time\nasync def f():\n    time.sleep(1)\n",
        "import time as t\nasync def f():\n    t.sleep(1)\n",
        "from time import sleep\nasync def f():\n    sleep(1)\n",
        "async def f(p):\n    open(p)\n",
        "import os\nasync def f(fd):\n    os.fsync(fd)\n",
    ],
)
def test_tps005_positive(tmp_path, src):
    res = _lint(tmp_path, {"scheduler.py": src}, select=["TPS005"])
    assert _rules_of(res) == ["TPS005"], render_table(res)


def test_tps005_negative(tmp_path):
    res = _lint(
        tmp_path,
        {
            "scheduler.py": (
                "import asyncio, time\n"
                "async def f():\n"
                "    await asyncio.sleep(1)\n"
                "    def worker():\n"
                "        time.sleep(1)  # runs on an executor thread\n"
                "    return worker\n"
                "def sync_helper(p):\n"
                "    return open(p)\n"
            ),
            # other modules may block freely
            "other.py": "import time\nasync def f():\n    time.sleep(1)\n",
        },
        select=["TPS005"],
    )
    assert res.findings == []


def test_tps005_waived(tmp_path):
    res = _lint(
        tmp_path,
        {
            "scheduler.py": (
                "import time\n"
                "async def f():\n"
                "    time.sleep(0)  # tpusnap: waive=TPS005 yield hack\n"
            )
        },
        select=["TPS005"],
    )
    assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------- TPS006


@pytest.mark.parametrize(
    "body",
    [
        "self._thread.join()",
        "self.close()",
        "self._monitor.stop()",
        "self._executor.shutdown()",
    ],
)
def test_tps006_del_positive(tmp_path, body):
    src = f"class C:\n    def __del__(self):\n        {body}\n"
    res = _lint(tmp_path, {"mod.py": src}, select=["TPS006"])
    assert _rules_of(res) == ["TPS006"], render_table(res)


@pytest.mark.parametrize(
    "src",
    [
        # executor joins in close() must route through the policy helper
        "class C:\n    def close(self):\n"
        "        self._ex.shutdown(wait=True)\n",
        "class C:\n    def close(self):\n        self._ex.shutdown()\n",
        "class C:\n    def close(self):\n        self._t.join()\n",
    ],
)
def test_tps006_close_positive(tmp_path, src):
    res = _lint(tmp_path, {"mod.py": src}, select=["TPS006"])
    assert _rules_of(res) == ["TPS006"], render_table(res)


def test_tps006_negative(tmp_path):
    res = _lint(
        tmp_path,
        {
            "a.py": (
                "from .io_types import finalizer_close_scope\n"
                "class C:\n"
                "    def __del__(self):\n"
                "        with finalizer_close_scope():\n"
                "            self.close()\n"
            ),
            "b.py": (
                "from .io_types import shutdown_plugin_executor\n"
                "class C:\n"
                "    def close(self):\n"
                "        shutdown_plugin_executor(self._ex)\n"
            ),
            "c.py": (
                "from .io_types import close_may_join\n"
                "class C:\n"
                "    def close(self):\n"
                "        self._ex.shutdown(wait=close_may_join())\n"
                "class D:\n"
                "    def close(self):\n"
                "        self._ex.shutdown(wait=False)\n"
            ),
            # string/path joins are not thread joins
            "d.py": (
                "import os\n"
                "class C:\n"
                "    def __del__(self):\n"
                '        x = ", ".join(self.names)\n'
                "    def close(self):\n"
                "        p = os.path.join(self.a, self.b)\n"
            ),
        },
        select=["TPS006"],
    )
    assert res.findings == [], render_table(res)


def test_tps006_waived(tmp_path):
    res = _lint(
        tmp_path,
        {
            "mod.py": (
                "class C:\n"
                "    def __del__(self):\n"
                "        self._t.join()  # tpusnap: waive=TPS006 daemon\n"
            )
        },
        select=["TPS006"],
    )
    assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------- TPS007


def test_tps007_undocumented_knob(tmp_path):
    res = _lint(
        tmp_path,
        {"knobs.py": '_FOO = "TPUSNAP_FOO"\n_BAR = "TPUSNAP_BAR"\n'},
        select=["TPS007"],
        api_md="| `TPUSNAP_FOO` | doc |\n",
    )
    assert _rules_of(res) == ["TPS007"]
    assert "TPUSNAP_BAR" in res.findings[0].message


def test_tps007_documented_but_dead_knob(tmp_path):
    res = _lint(
        tmp_path,
        {"knobs.py": '_FOO = "TPUSNAP_FOO"\n'},
        select=["TPS007"],
        api_md="| `TPUSNAP_FOO` | doc |\n| `TPUSNAP_GONE` | doc |\n",
    )
    assert _rules_of(res) == ["TPS007"]
    assert "TPUSNAP_GONE" in res.findings[0].message
    assert res.findings[0].path == "docs/api.md"


def test_tps007_clean_and_missing_docs(tmp_path):
    res = _lint(
        tmp_path,
        {"knobs.py": '_FOO = "TPUSNAP_FOO"\n'},
        select=["TPS007"],
        api_md="| `TPUSNAP_FOO` | doc |\n",
    )
    assert res.findings == []
    # No docs/ directory at all = an installed copy, not a checkout:
    # the drift check skips instead of failing a clean install.
    res = _lint(
        tmp_path / "nodocs",
        {"knobs.py": '_FOO = "TPUSNAP_FOO"\n'},
        select=["TPS007"],
    )
    assert res.findings == []
    # docs/ present but api.md unreadable = a checkout that lost the
    # file: that IS a finding.
    base = tmp_path / "docsonly"
    base.mkdir()
    (base / "docs").mkdir()
    res = _lint(
        base, {"knobs.py": '_FOO = "TPUSNAP_FOO"\n'}, select=["TPS007"]
    )
    assert _rules_of(res) == ["TPS007"]


# ----------------------------------------------- the whole-package gate


def test_whole_package_zero_findings():
    """The tier-1 lint gate: the shipped tree is clean under every rule.
    (Waivers are allowed — they are deliberate, documented exceptions —
    but unwaived findings fail.)"""
    res = run_lint()
    assert res.findings == [], "\n" + render_table(res)
    # sanity: the gate actually scanned the real package
    assert res.files_scanned > 40
    assert set(res.rules_run) == {
        "TPS001", "TPS002", "TPS003", "TPS004", "TPS005", "TPS006", "TPS007"
    }


# ------------------------------------------------------------- CLI gate


def _cli(argv):
    from tpusnap.__main__ import main

    return main(argv)


@pytest.fixture()
def package_copy(tmp_path):
    """A temp copy of the real package + docs, lint-clean by
    construction (asserted), ready for violation seeding."""
    dst = tmp_path / "tpusnap"
    shutil.copytree(
        os.path.join(REPO, "tpusnap"),
        dst,
        ignore=shutil.ignore_patterns("__pycache__", "*.pyc"),
    )
    shutil.copytree(os.path.join(REPO, "docs"), tmp_path / "docs")
    assert _cli(["lint", "--check", "--root", str(dst)]) == 0
    return dst


def test_cli_clean_tree_exits_0(capsys):
    assert _cli(["lint", "--check"]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_cli_json_shape(capsys):
    assert _cli(["lint", "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["clean"] is True
    assert data["files_scanned"] > 40
    assert isinstance(data["waived"], list)


SEEDS = {
    "TPS001": (
        "analyze.py",
        'import os\n_SEEDED = os.environ.get("TPUSNAP_SEEDED")\n',
    ),
    "TPS002": ("telemetry.py", "import time\n_SEEDED = time.time()\n"),
    "TPS003": ("progress.py", '_SEEDED = ".tpusnap" "/seeded"\n'),
    "TPS004": (
        "comm.py",
        "def _seeded():\n"
        "    try:\n"
        "        raise RuntimeError()\n"
        "    except Exception:\n"
        "        pass\n",
    ),
    "TPS005": (
        "scheduler.py",
        "import time as _seeded_time\n"
        "async def _seeded():\n"
        "    _seeded_time.sleep(0.01)\n",
    ),
    "TPS006": (
        "lifecycle.py",
        "class _Seeded:\n"
        "    def __del__(self):\n"
        "        self._thread.join()\n",
    ),
    "TPS007": ("knobs.py", '_SEEDED_ENV = "TPUSNAP_SEEDED_UNDOCUMENTED"\n'),
}


@pytest.mark.parametrize("rule", sorted(SEEDS))
def test_cli_seeded_violation_exits_2(package_copy, capsys, rule):
    """Each shipped rule actually fires: seed one violation of it into
    a (verified-clean) temp copy and the gate exits 2 naming the rule."""
    relpath, snippet = SEEDS[rule]
    target = package_copy / relpath
    target.write_text(target.read_text() + "\n" + snippet)
    rc = _cli(["lint", "--check", "--root", str(package_copy)])
    out = capsys.readouterr().out
    assert rc == 2, out
    assert rule in out


def test_cli_subprocess_smoke():
    """The real entry point end to end: `python -m tpusnap lint --check`
    on the shipped tree exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "tpusnap", "lint", "--check"],
        cwd=REPO,
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
