"""S3 plugin tests against an in-memory stub client.

The reference gates S3 tests behind a real bucket
(/root/reference/tests/test_s3_storage_plugin.py:29-49); aiobotocore is
not available here, so a stub client exercises the plugin's logic: key
prefixing, body handling for memoryview/bytes, inclusive Range-header
formatting, and delete.
"""

import asyncio
import io

import pytest

from tpusnap.io_types import ReadIO, WriteIO
from tpusnap.storage_plugins.s3 import S3StoragePlugin


class _Body:
    def __init__(self, data: bytes):
        self._data = data

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False

    async def read(self):
        return self._data


class StubS3Client:
    def __init__(self):
        self.objects = {}
        self.calls = []

    async def put_object(self, Bucket, Key, Body):
        self.calls.append(("put", Bucket, Key))
        data = Body.read() if hasattr(Body, "read") else bytes(Body)
        self.objects[(Bucket, Key)] = bytes(data)

    async def get_object(self, Bucket, Key, Range=None):
        self.calls.append(("get", Bucket, Key, Range))
        data = self.objects[(Bucket, Key)]
        if Range is not None:
            assert Range.startswith("bytes=")
            lo, hi = Range[len("bytes=") :].split("-")
            data = data[int(lo) : int(hi) + 1]  # HTTP Range is inclusive
        return {"Body": _Body(data)}

    async def delete_object(self, Bucket, Key):
        self.calls.append(("delete", Bucket, Key))
        self.objects.pop((Bucket, Key), None)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture()
def plugin():
    p = S3StoragePlugin("mybucket/some/prefix")
    p._client = StubS3Client()
    return p


def test_construction_parses_root():
    p = S3StoragePlugin("bucket/deep/prefix")
    assert p.bucket == "bucket" and p.root == "deep/prefix"
    with pytest.raises(ValueError):
        S3StoragePlugin("bucketonly")


def test_write_read_round_trip(plugin):
    payload = bytes(range(256)) * 10
    _run(plugin.write(WriteIO(path="rank0/w", buf=memoryview(payload))))
    assert plugin._client.objects[("mybucket", "some/prefix/rank0/w")] == payload
    read_io = ReadIO(path="rank0/w")
    _run(plugin.read(read_io))
    assert read_io.buf.getvalue() == payload


def test_bytes_body(plugin):
    _run(plugin.write(WriteIO(path="b", buf=b"hello")))
    assert plugin._client.objects[("mybucket", "some/prefix/b")] == b"hello"


def test_ranged_read_inclusive_header(plugin):
    payload = bytes(range(200))
    _run(plugin.write(WriteIO(path="r", buf=memoryview(payload))))
    read_io = ReadIO(path="r", byte_range=(10, 60))
    _run(plugin.read(read_io))
    assert read_io.buf.getvalue() == payload[10:60]
    get_call = [c for c in plugin._client.calls if c[0] == "get"][0]
    assert get_call[3] == "bytes=10-59"  # end-exclusive -> inclusive


def test_delete(plugin):
    _run(plugin.write(WriteIO(path="d", buf=b"x")))
    _run(plugin.delete("d"))
    assert ("mybucket", "some/prefix/d") not in plugin._client.objects


def test_in_place_read_with_fused_crc(plugin):
    """ReadIO.into lands the body directly in the destination with the
    checksum computed off-loop; consumers then verify a 4-byte value."""
    import numpy as np

    from tpusnap import _native

    payload = bytes(range(256)) * 8
    _run(plugin.write(WriteIO(path="obj", buf=payload)))

    dst = np.zeros(len(payload), dtype=np.uint8)
    read_io = ReadIO(path="obj", into=memoryview(dst), want_crc=True)
    _run(plugin.read(read_io))
    assert read_io.in_place
    assert dst.tobytes() == payload
    assert read_io.crc32c == _native.crc32c(payload)
    assert read_io.crc_algo == _native.checksum_algorithm()
    assert bytes(read_io.buf.getbuffer()) == payload

    # byte-ranged in-place read
    dst2 = np.zeros(500, dtype=np.uint8)
    read_io = ReadIO(
        path="obj", byte_range=(100, 600), into=memoryview(dst2), want_crc=True
    )
    _run(plugin.read(read_io))
    assert dst2.tobytes() == payload[100:600]
    assert read_io.crc32c == _native.crc32c(payload[100:600])


def test_in_place_size_mismatch_fails_loudly(plugin):
    """A truncated stored object must raise, not silently fall back to
    an unbudgeted full-size buffer."""
    import numpy as np

    _run(plugin.write(WriteIO(path="obj", buf=b"x" * 100)))
    dst = np.zeros(200, dtype=np.uint8)  # manifest said 200, object has 100
    read_io = ReadIO(path="obj", into=memoryview(dst), want_crc=True)
    with pytest.raises(IOError, match="truncated"):
        _run(plugin.read(read_io))
