"""Doc/code drift guards for the observability surface:

1. Knob drift — every ``TPUSNAP_*`` env var defined in tpusnap/knobs.py
   must appear in docs/api.md, and every knob row in api.md's knob
   table must be referenced somewhere in the package source. Fails
   naming the missing knobs (the acceptance criterion of the fleet
   observability PR's doc-drift satellite).
2. Monotonic-only lint — ``time.time()`` calls are forbidden in
   tpusnap/telemetry.py, tpusnap/progress.py and tpusnap/history.py:
   duration/throttle math in those files must run on the monotonic
   clock (PR 2's invariant), and wall-clock TIMESTAMPS must go through
   each module's injectable ``_wall``/``wall_clock`` seam (a bare
   ``time.time`` reference, never a direct call) so fake-clock tests
   stay possible and a copy-pasted ``time.time()`` in duration math is
   caught by grep, not by a flaky 2 a.m. incident.
"""

import glob
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(*parts):
    with open(os.path.join(REPO, *parts)) as f:
        return f.read()


def test_every_knob_in_knobs_py_is_documented():
    defined = set(
        re.findall(r'"(TPUSNAP_[A-Z0-9_]+)"', _read("tpusnap", "knobs.py"))
    )
    assert defined, "no knobs found — did knobs.py move?"
    docs = _read("docs", "api.md")
    missing = sorted(n for n in defined if n not in docs)
    assert not missing, (
        "knobs defined in tpusnap/knobs.py but undocumented in "
        f"docs/api.md: {missing}"
    )


def test_every_documented_knob_exists_in_source():
    docs = _read("docs", "api.md")
    table_rows = re.findall(r"^\|\s*`(TPUSNAP_[A-Z0-9_]+)`", docs, re.M)
    assert table_rows, "no knob table rows found — did api.md move?"
    source = "".join(
        _read(p)
        for p in glob.glob(
            os.path.join(REPO, "tpusnap", "**", "*.py"), recursive=True
        )
    )
    missing = sorted(n for n in set(table_rows) if n not in source)
    assert not missing, (
        "knobs documented in docs/api.md but referenced nowhere in "
        f"tpusnap/: {missing}"
    )


def test_monotonic_only_no_time_time_calls():
    offenders = {}
    for name in ("telemetry.py", "progress.py", "history.py"):
        src = _read("tpusnap", name)
        lines = [
            i
            for i, ln in enumerate(src.splitlines(), 1)
            if "time.time()" in ln
        ]
        if lines:
            offenders[name] = lines
    assert not offenders, (
        f"direct time.time() calls in monotonic-only modules {offenders}: "
        "durations must use time.monotonic(); wall timestamps must go "
        "through the module's injectable _wall / wall_clock seam"
    )
