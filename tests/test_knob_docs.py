"""Doc/code drift guards for the observability surface — now thin
wrappers over the lint engine so there is ONE rule implementation, not
three ad-hoc greps:

1. Knob drift (TPS007, ``tpusnap/devtools/rules/tps007_knob_docs.py``) —
   every ``TPUSNAP_*`` env var defined in tpusnap/knobs.py must appear
   in docs/api.md, and every knob row in api.md's table must be
   referenced somewhere in the package source.
2. Monotonic-only clocks (TPS002, ``rules/tps002_monotonic.py``) —
   direct wall-clock CALLS are forbidden in telemetry/progress/history;
   timestamps ride each module's injectable ``_wall`` seam (a bare
   ``time.time`` reference). The AST rule also catches the aliased
   imports (``from time import time``) the original grep missed.

Kept as named tests (not just the whole-package gate in test_lint.py)
so a drift failure points at the invariant by name."""

from tpusnap.devtools.lint import render_table, run_lint


def _run_rule(rule_id):
    result = run_lint(select=[rule_id])
    assert result.rules_run == [rule_id]
    return result


def test_knob_doc_drift_tps007():
    result = _run_rule("TPS007")
    assert result.findings == [], "\n" + render_table(result)


def test_monotonic_only_clocks_tps002():
    result = _run_rule("TPS002")
    assert result.findings == [], "\n" + render_table(result)
