"""Write-back storage tiering: durable-local commit + crash-safe,
outage-tolerant background cloud drain (tpusnap/tiering.py).

Covers the acceptance criteria end to end:

- a tiered take against a chaos-unavailable remote commits at local
  speed (wall bounded against a plain local take) and never fails;
- SIGKILL mid-upload-drain → fsck says ``local-committed``; a resumed
  drain converges to ``remote-durable`` with ≥50% of the upload bytes
  skipped via journal evidence;
- SIGKILL mid-gc-of-drained-local-blobs → the remote-durable snapshot
  stays restorable from the remote;
- the chaos outage-window soak: takes never block, the lag gauges rise
  while degraded and fall to zero on recovery;
- plus the satellites: the ``outage`` fault kind, retry-budget
  exhaustion accounting, and the tier-aware RTO estimator.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusnap import Snapshot, StateDict, knobs, telemetry, tiering
from tpusnap.faults import FaultPlan
from tpusnap.io_types import UPLOAD_JOURNAL_PATH, ReadIO, StoragePlugin, WriteIO
from tpusnap.lifecycle import fsck_snapshot, gc_snapshot
from tpusnap.storage_plugin import (
    register_storage_plugin,
    unregister_storage_plugin,
    url_to_storage_plugin,
)
from tpusnap.tiering import (
    DrainReport,
    drain_snapshot,
    parse_tier_url,
    read_upload_journal_dir,
    restore_source_label,
    tier_state_of_dir,
)

pytestmark = pytest.mark.tiering

_N = 6
_SHAPE = (64, 64)


def _state(seed: int = 0):
    return {
        "m": StateDict(
            **{
                f"w{i}": np.random.default_rng(seed * 100 + i)
                .standard_normal(_SHAPE)
                .astype(np.float32)
                for i in range(_N)
            }
        )
    }


def _zeros():
    return {
        "m": StateDict(
            **{f"w{i}": np.zeros(_SHAPE, np.float32) for i in range(_N)}
        )
    }


def _assert_eq(a, b):
    for k in a["m"]:
        assert np.array_equal(np.asarray(a["m"][k]), np.asarray(b["m"][k])), k


def _tier_url(tmp_path, name="snap", remote_scheme="fs"):
    cache = os.path.join(str(tmp_path), "cache")
    remote_root = os.path.join(str(tmp_path), "remote")
    return (
        f"tier+local={cache}+remote={remote_scheme}://{remote_root}/{name}",
        os.path.join(str(tmp_path), "remote", name),
    )


@pytest.fixture(autouse=True)
def _isolated_tier_env(tmp_path, monkeypatch):
    """Each test gets its own telemetry dir (the tier status sidecar
    lives there) and a quiet, manually-driven drain by default."""
    monkeypatch.setenv("TPUSNAP_TELEMETRY_DIR", str(tmp_path / "tele"))
    monkeypatch.setenv("TPUSNAP_TIER_DRAIN", "0")
    monkeypatch.setenv("TPUSNAP_HISTORY", "0")
    yield
    tiering.reset_manager_for_tests()


# ------------------------------------------------------------- URL parsing


def test_parse_tier_url_basic():
    spec = parse_tier_url("tier+local=/nvme/cache+remote=s3://bucket/run1")
    assert spec is not None
    assert spec.local_base == "/nvme/cache"
    assert spec.remote_url == "s3://bucket/run1"
    assert spec.local_dir == "/nvme/cache/bucket/run1"


def test_parse_tier_url_composed_remote_and_suffix():
    spec = parse_tier_url(
        "tier+local=/c+remote=chaos+fsspec+memory://root/run/inc_0001"
    )
    assert spec.remote_scheme == "chaos+fsspec+memory"
    # Appending a member suffix to the URL extends BOTH tiers.
    assert spec.local_dir == "/c/root/run/inc_0001"
    assert spec.remote_url == "chaos+fsspec+memory://root/run/inc_0001"


def test_parse_tier_url_rejects_malformed():
    assert parse_tier_url("fs:///plain") is None
    assert parse_tier_url("/plain/dir") is None
    with pytest.raises(ValueError):
        parse_tier_url("tier+remote=s3://b/x")
    with pytest.raises(ValueError):
        parse_tier_url("tier+local=+remote=s3://b/x")


def test_chaos_around_whole_tier_refused(tmp_path):
    url, _ = _tier_url(tmp_path)
    with pytest.raises(RuntimeError, match="remote sub-scheme"):
        url_to_storage_plugin("chaos+" + url)


# ------------------------------------------------------- plugin semantics


def test_writes_stay_local_reads_fall_back(tmp_path):
    url, remote_dir = _tier_url(tmp_path)
    plugin = url_to_storage_plugin(url)
    local_dir = plugin.local_dir
    try:
        plugin.sync_write(WriteIO(path="blob/a", buf=b"payload-bytes"))
        assert os.path.exists(os.path.join(local_dir, "blob/a"))
        assert not os.path.exists(os.path.join(remote_dir, "blob/a"))

        # Sidecar miss must NOT consult the remote (it would put a
        # possibly-down endpoint on the take path): plain miss.
        probe = ReadIO(path=UPLOAD_JOURNAL_PATH + ".absent")
        with pytest.raises(FileNotFoundError):
            plugin.sync_read(probe)

        # A blob present only remotely reads through.
        os.makedirs(os.path.join(remote_dir, "blob"), exist_ok=True)
        with open(os.path.join(remote_dir, "blob/b"), "wb") as f:
            f.write(b"remote-only")
        rio = ReadIO(path="blob/b")
        plugin.sync_read(rio)
        assert rio.buf.getvalue() == b"remote-only"

        # Deletes propagate to both tiers (remote-only file included).
        plugin.sync_delete("blob/b")
        assert not os.path.exists(os.path.join(remote_dir, "blob/b"))
    finally:
        plugin.sync_close()


def test_listing_is_local_only(tmp_path):
    url, remote_dir = _tier_url(tmp_path)
    plugin = url_to_storage_plugin(url)
    try:
        plugin.sync_write(WriteIO(path="x", buf=b"1"))
        os.makedirs(remote_dir, exist_ok=True)
        with open(os.path.join(remote_dir, "remote_only"), "wb") as f:
            f.write(b"2")
        files = plugin.sync_list_with_sizes()
        assert "x" in files and "remote_only" not in files
    finally:
        plugin.sync_close()


# ----------------------------------------- take → drain → remote-durable


def test_take_drain_restore_roundtrip(tmp_path):
    url, remote_dir = _tier_url(tmp_path)
    state = _state()
    Snapshot.take(url, state)
    local_dir = parse_tier_url(url).local_dir

    rep = fsck_snapshot(local_dir)
    assert rep.state == "committed"
    assert rep.durability == "local-committed"
    assert rep.tier_remote.endswith("/snap")
    # Nothing reached the remote yet (drain disabled).
    assert not os.path.exists(os.path.join(remote_dir, ".snapshot_metadata"))

    report = drain_snapshot(url)
    assert report.state == "durable"
    assert report.blobs_uploaded == report.blobs_total > 0
    assert report.lag_bytes == 0

    rep2 = fsck_snapshot(local_dir)
    assert rep2.durability == "remote-durable"
    # The upload journal is a legit post-commit sidecar, not an orphan.
    assert UPLOAD_JOURNAL_PATH not in rep2.orphans

    # The REMOTE tier is a self-contained committed snapshot.
    restored = _zeros()
    Snapshot(remote_dir).restore(restored)
    _assert_eq(state, restored)

    # Idempotent re-drain: everything skips on journal evidence.
    again = drain_snapshot(url)
    assert again.state == "durable"
    assert again.blobs_uploaded == 0
    assert again.blobs_skipped == report.blobs_total


def test_background_drain_on_commit(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAP_TIER_DRAIN", "1")
    url, remote_dir = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    assert tiering.drain_manager().wait_idle(timeout=60)
    st = tier_state_of_dir(parse_tier_url(url).local_dir)
    assert st["durability"] == "remote-durable"
    assert st["lag_bytes"] == 0
    assert os.path.exists(os.path.join(remote_dir, ".snapshot_metadata"))


def test_upload_journal_alone_is_not_foreign(tmp_path):
    d = str(tmp_path / "dir")
    os.makedirs(os.path.join(d, os.path.dirname(UPLOAD_JOURNAL_PATH)))
    with open(os.path.join(d, UPLOAD_JOURNAL_PATH), "w") as f:
        json.dump({"version": 1, "remote": "s3://b/x", "blobs": {}}, f)
    rep = fsck_snapshot(d)
    assert rep.state == "empty"


# -------------------------------------------------- resume / skip-on-resume


class _FailAfterK(StoragePlugin):
    """Remote double that accepts K payload writes then hard-fails
    (non-transient) — a deterministic in-process partial drain."""

    budget = {"n": 0}

    def __init__(self, inner):
        self.inner = inner

    async def write(self, write_io):
        if self.budget["n"] <= 0:
            raise OSError(5, "remote exploded")  # EIO: classified fatal
        self.budget["n"] -= 1
        await self.inner.write(write_io)

    async def write_atomic(self, write_io, durable=False):
        if self.budget["n"] <= 0:
            raise OSError(5, "remote exploded")
        self.budget["n"] -= 1
        await self.inner.write_atomic(write_io, durable=durable)

    async def read(self, read_io):
        await self.inner.read(read_io)

    async def delete(self, path):
        await self.inner.delete(path)

    async def list_with_sizes(self):
        return await self.inner.list_with_sizes()

    async def close(self):
        await self.inner.close()


def test_drain_resume_skips_proven_blobs(tmp_path, monkeypatch):
    """Partial drain (remote dies after K uploads) → degraded; the
    resumed drain re-uploads ONLY the unproven remainder (≥50% of the
    bytes skip on journal evidence)."""
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    remote_root = str(tmp_path / "remote_fk")

    def factory(path, storage_options):
        return _FailAfterK(FSStoragePlugin(root=os.path.join(remote_root, path)))

    register_storage_plugin("failk", factory)
    try:
        cache = str(tmp_path / "cache")
        url = f"tier+local={cache}+remote=failk://snap"
        # Many small blobs: slab batching off so each array is its own
        # upload unit.
        with knobs.override_batching_disabled(True):
            Snapshot.take(url, _state())
        local_dir = parse_tier_url(url).local_dir

        _FailAfterK.budget["n"] = 4  # enough for 4 of the 6+ blobs
        with knobs.override_tier_outage(threshold=1, backoff_cap_s=0.05):
            partial = drain_snapshot(url, deadline_s=2.0)
        assert partial.state == "degraded"
        assert partial.blobs_uploaded == 4
        assert partial.degraded_episodes >= 1
        assert partial.lag_bytes > 0
        # fsck still says local-committed: durability never lies.
        assert fsck_snapshot(local_dir).durability == "local-committed"

        _FailAfterK.budget["n"] = 10**9  # remote healthy again
        resumed = drain_snapshot(url)
        assert resumed.state == "durable"
        assert resumed.blobs_skipped == 4
        total = resumed.bytes_skipped + resumed.bytes_uploaded
        assert resumed.bytes_skipped >= total * 0.5
        restored = _zeros()
        Snapshot(os.path.join(remote_root, "snap")).restore(restored)
        _assert_eq(_state(), restored)
    finally:
        unregister_storage_plugin("failk")


class _StampOnFirstWrite(StoragePlugin):
    """Remote double that, on its first payload write, re-stamps the
    LOCAL upload journal's committed_at — deterministically simulating
    a retake committing to the dir while the drain is mid-flight."""

    hooks = {"local_dir": None, "fired": False}

    def __init__(self, inner):
        self.inner = inner

    async def write(self, write_io):
        if not self.hooks["fired"]:
            self.hooks["fired"] = True
            jpath = os.path.join(self.hooks["local_dir"], UPLOAD_JOURNAL_PATH)
            with open(jpath) as f:
                journal = json.load(f)
            journal["committed_at"] = (journal.get("committed_at") or 0) + 99.0
            journal["state"] = "pending"
            with open(jpath, "w") as f:
                json.dump(journal, f)
        await self.inner.write(write_io)

    async def write_atomic(self, write_io, durable=False):
        await self.inner.write_atomic(write_io, durable=durable)

    async def read(self, read_io):
        await self.inner.read(read_io)

    async def delete(self, path):
        await self.inner.delete(path)

    async def list_with_sizes(self):
        return await self.inner.list_with_sizes()

    async def close(self):
        await self.inner.close()


def test_concurrent_retake_never_clobbered_by_durable_marker(tmp_path):
    """A retake committing WHILE a drain runs must not end up falsely
    remote-durable: the drain's journal flushes merge (the new pending
    stamp survives) and the durable marker is refused (superseded)."""
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    remote_root = str(tmp_path / "remote_stamp")

    def factory(path, storage_options):
        return _StampOnFirstWrite(
            FSStoragePlugin(root=os.path.join(remote_root, path))
        )

    register_storage_plugin("stampfs", factory)
    try:
        cache = str(tmp_path / "cache")
        url = f"tier+local={cache}+remote=stampfs://snap"
        Snapshot.take(url, _state())
        local_dir = parse_tier_url(url).local_dir
        _StampOnFirstWrite.hooks.update(local_dir=local_dir, fired=False)

        report = drain_snapshot(url)
        assert report.state == "superseded", report.summary()
        journal = read_upload_journal_dir(local_dir)
        # The concurrent commit's stamp survived every flush and the
        # durability state stayed honest.
        assert journal["state"] == "pending"
        assert fsck_snapshot(local_dir).durability == "local-committed"
        # Evidence still accumulated: the follow-up drain skips it all
        # and converges.
        converged = drain_snapshot(url)
        assert converged.state == "durable"
        assert converged.blobs_uploaded == 0
        assert converged.blobs_skipped == report.blobs_uploaded
    finally:
        unregister_storage_plugin("stampfs")


def test_retake_first_write_clears_commit_stamp(tmp_path):
    """The seed of a RETAKE must drop the previous take's commit stamp:
    an in-flight drain of take N gates its durable marker on that
    stamp, and a stale one surviving into take N+1's pre-commit window
    would let the drain bless the dir while N+1 overwrites payload."""
    url, _ = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir
    assert read_upload_journal_dir(local_dir)["committed_at"] is not None
    # Simulate the retake's FIRST blob write (before any commit).
    plugin = url_to_storage_plugin(url)
    try:
        plugin.sync_write(WriteIO(path="0/m/w0", buf=b"new-bytes"))
    finally:
        plugin.sync_close()
    journal = read_upload_journal_dir(local_dir)
    assert journal["state"] == "pending"
    assert journal.get("committed_at") is None  # stamp gone with the seed


def test_delete_surfaces_real_local_failure(tmp_path, monkeypatch):
    """A non-FileNotFoundError local delete failure must raise even
    when the remote delete succeeds — otherwise gc/retention report
    bytes reclaimed that still occupy the local disk."""
    from tpusnap.storage_plugins import fs as fs_mod

    url, remote_dir = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    assert drain_snapshot(url).state == "durable"
    plugin = url_to_storage_plugin(url)
    orig = fs_mod.FSStoragePlugin.delete

    async def deny_local(self, path):
        if self.root.startswith(str(tmp_path / "cache")):
            raise PermissionError(13, "read-only local tier")
        await orig(self, path)

    monkeypatch.setattr(fs_mod.FSStoragePlugin, "delete", deny_local)
    try:
        with pytest.raises(PermissionError):
            plugin.sync_delete(".snapshot_metadata")
        # Evicted blob (genuine local miss) still deletes via remote.
        monkeypatch.undo()
    finally:
        plugin.sync_close()


def test_manager_requeues_enqueue_during_active_drain(tmp_path, monkeypatch):
    """enqueue() for a dir whose drain is ACTIVE must re-run after it —
    a retake's bytes must not stay local-committed forever."""
    import threading

    from tpusnap.storage_plugins.fs import FSStoragePlugin

    remote_root = str(tmp_path / "remote_slow")
    gate = threading.Event()
    started = threading.Event()

    class _Slow(StoragePlugin):
        def __init__(self, inner):
            self.inner = inner

        async def write(self, write_io):
            started.set()
            import asyncio as _a

            while not gate.is_set():
                await _a.sleep(0.01)
            await self.inner.write(write_io)

        async def write_atomic(self, write_io, durable=False):
            await self.inner.write_atomic(write_io, durable=durable)

        async def read(self, read_io):
            await self.inner.read(read_io)

        async def delete(self, path):
            await self.inner.delete(path)

        async def list_with_sizes(self):
            return await self.inner.list_with_sizes()

        async def close(self):
            await self.inner.close()

    def factory(path, storage_options):
        return _Slow(FSStoragePlugin(root=os.path.join(remote_root, path)))

    register_storage_plugin("slowfs", factory)
    try:
        cache = str(tmp_path / "cache")
        url = f"tier+local={cache}+remote=slowfs://snap"
        Snapshot.take(url, _state())
        local_dir = parse_tier_url(url).local_dir
        mgr = tiering.drain_manager()
        mgr.enqueue(local_dir, "slowfs://snap", None)
        assert started.wait(timeout=30), "drain never started"
        # Retake while the drain is stuck inside its first upload: the
        # journal gets a new stamp, and the enqueue lands mid-active.
        Snapshot.take(url, _state(seed=1))
        mgr.enqueue(local_dir, "slowfs://snap", None)
        gate.set()
        assert mgr.wait_idle(timeout=60)
        journal = read_upload_journal_dir(local_dir)
        assert journal["state"] == "durable"
        restored = _zeros()
        Snapshot(os.path.join(remote_root, "snap")).restore(restored)
        _assert_eq(_state(seed=1), restored)  # the RETAKE's bytes
    finally:
        unregister_storage_plugin("slowfs")


def test_slo_check_ignores_stale_degraded_flag(tmp_path):
    """A dead uploader's last degraded status must not fail the gate
    forever: older than the freshness window → surfaced, not gated."""
    import time as _time

    tele = os.environ["TPUSNAP_TELEMETRY_DIR"]
    # A healthy SLO record so the gate has something green to grade.
    slo_dir = os.path.join(tele, "slo")
    os.makedirs(slo_dir, exist_ok=True)
    with open(os.path.join(slo_dir, "rank_0.json"), "w") as f:
        json.dump(
            {
                "v": 1,
                "rank": 0,
                "world_size": 1,
                "ts": _time.time(),
                "started_ts": _time.time() - 10,
                "last_commit_ts": _time.time() - 1,
                "data_at_risk_bytes": 0,
                "final": True,
            },
            f,
        )
    tier_dir = os.path.join(tele, "tier")
    os.makedirs(tier_dir, exist_ok=True)
    stale = {
        "state": "degraded",
        "degraded": True,
        "lag_bytes": 999,
        "lag_seconds": 5000.0,
        "remote": "s3://b/x",
        "ts": _time.time() - 86400,  # a day old: uploader long gone
    }
    with open(os.path.join(tier_dir, "status.json"), "w") as f:
        json.dump(stale, f)
    r = _cli("slo", "--check", "--rpo", "3600")
    assert r.returncode == 0, r.stdout + r.stderr
    # A FRESH degraded flag still gates.
    stale["ts"] = _time.time()
    with open(os.path.join(tier_dir, "status.json"), "w") as f:
        json.dump(stale, f)
    r = _cli("slo", "--check", "--rpo", "3600")
    assert r.returncode == 2, r.stdout + r.stderr


# --------------------------------------------------- chain-aware draining


def test_drain_uploads_incremental_base_first(tmp_path):
    cache = str(tmp_path / "cache")
    remote_root = str(tmp_path / "remote")
    base_url = f"tier+local={cache}+remote=fs://{remote_root}/base"
    inc_url = f"tier+local={cache}+remote=fs://{remote_root}/inc"
    state = _state()
    Snapshot.take(base_url, state)
    state["m"]["w0"] = state["m"]["w0"] + 1.0
    Snapshot.take(
        inc_url, state, incremental_from=parse_tier_url(base_url).local_dir
    )

    report = drain_snapshot(inc_url)
    assert report.state == "durable"
    # The base drained first, to its remote sibling.
    assert report.bases and report.bases[0].state == "durable"
    assert os.path.exists(os.path.join(remote_root, "base", ".snapshot_metadata"))
    restored = _zeros()
    Snapshot(os.path.join(remote_root, "inc")).restore(restored)
    _assert_eq(state, restored)


def test_drain_skips_orphans_and_zero_byte_blobs(tmp_path):
    """Only manifest-referenced blobs drain (orphans/.tmp debris are
    gc's business, not cloud spend), and tiny/empty referenced blobs
    skip on evidence like any other — a fully-proven snapshot re-drains
    with zero uploads."""
    url, remote_dir = _tier_url(tmp_path)
    state = _state()
    state["m"]["empty"] = np.zeros((0,), np.float32)
    Snapshot.take(url, state)
    local_dir = parse_tier_url(url).local_dir
    # Plant an orphan and flush debris next to the payload.
    with open(os.path.join(local_dir, "orphan_blob"), "wb") as f:
        f.write(b"x" * 512)
    with open(os.path.join(local_dir, "0.tmp.999"), "wb") as f:
        f.write(b"y" * 512)
    report = drain_snapshot(url)
    assert report.state == "durable"
    assert not os.path.exists(os.path.join(remote_dir, "orphan_blob"))
    assert not os.path.exists(os.path.join(remote_dir, "0.tmp.999"))
    # Orphans don't count as upload lag either.
    assert tier_state_of_dir(local_dir)["lag_bytes"] == 0
    again = drain_snapshot(url)
    assert again.blobs_uploaded == 0


def test_malformed_journal_evidence_rereads_not_crashes(tmp_path):
    url, _ = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir
    jpath = os.path.join(local_dir, UPLOAD_JOURNAL_PATH)
    with open(jpath, "w") as f:
        json.dump(
            {"version": 1, "remote": "ignored", "blobs": {"0/m/w0": 42}}, f
        )
    # Malformed evidence reads as absent (re-upload), never a crash.
    assert read_upload_journal_dir(local_dir)["blobs"] == {}
    report = drain_snapshot(url)
    assert report.state == "durable"
    assert report.blobs_uploaded == report.blobs_total


def test_drain_refuses_durable_with_unreachable_blobs(tmp_path):
    """A referenced blob neither present locally nor journal-proven
    must block the durable marker (the remote could not restore)."""
    url, _ = _tier_url(tmp_path)
    with knobs.override_batching_disabled(True):
        Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir
    victim = next(
        os.path.join(dp, f)
        for dp, _dn, fn in os.walk(os.path.join(local_dir, "0"))
        for f in fn
    )
    os.remove(victim)
    report = drain_snapshot(url)
    assert report.state == "missing-blobs"
    assert fsck_snapshot(local_dir).durability == "local-committed"


def test_base_short_circuits_once_durable(tmp_path):
    """A delta/incremental drain must not re-hash its whole durable
    base chain on every micro-commit: the base recursion short-circuits
    on the base's durable marker."""
    cache = str(tmp_path / "cache")
    remote_root = str(tmp_path / "remote")
    base_url = f"tier+local={cache}+remote=fs://{remote_root}/base"
    inc_url = f"tier+local={cache}+remote=fs://{remote_root}/inc"
    state = _state()
    Snapshot.take(base_url, state)
    state["m"]["w0"] = state["m"]["w0"] + 1.0
    Snapshot.take(
        inc_url, state, incremental_from=parse_tier_url(base_url).local_dir
    )
    first = drain_snapshot(inc_url)
    assert first.state == "durable"
    assert first.bases[0].blobs_total > 0  # base actually drained
    second = drain_snapshot(inc_url)
    assert second.state == "durable"
    # Short-circuited: no blob pass ran against the base at all.
    assert second.bases[0].blobs_total == 0
    assert second.bases[0].blobs_skipped == 0


def test_queued_backlog_counts_in_lag(tmp_path, monkeypatch):
    """tpusnap_upload_lag_bytes covers the QUEUE, not just the active
    job: snapshots piling up behind a stuck drain are exposure too."""
    import threading

    from tpusnap.storage_plugins.fs import FSStoragePlugin

    remote_root = str(tmp_path / "remote_q")
    gate = threading.Event()
    started = threading.Event()

    class _Gated(StoragePlugin):
        def __init__(self, inner):
            self.inner = inner

        async def write(self, write_io):
            started.set()
            import asyncio as _a

            while not gate.is_set():
                await _a.sleep(0.01)
            await self.inner.write(write_io)

        async def write_atomic(self, write_io, durable=False):
            await self.inner.write_atomic(write_io, durable=durable)

        async def read(self, read_io):
            await self.inner.read(read_io)

        async def delete(self, path):
            await self.inner.delete(path)

        async def list_with_sizes(self):
            return await self.inner.list_with_sizes()

        async def close(self):
            await self.inner.close()

    def factory(path, storage_options):
        return _Gated(FSStoragePlugin(root=os.path.join(remote_root, path)))

    register_storage_plugin("gatedfs", factory)
    try:
        cache = str(tmp_path / "cache")
        url_a = f"tier+local={cache}+remote=gatedfs://a"
        url_b = f"tier+local={cache}+remote=gatedfs://b"
        Snapshot.take(url_a, _state())
        Snapshot.take(url_b, _state(seed=1))
        mgr = tiering.drain_manager()
        mgr.enqueue(parse_tier_url(url_a).local_dir, "gatedfs://a", None)
        assert started.wait(timeout=30)
        mgr.enqueue(parse_tier_url(url_b).local_dir, "gatedfs://b", None)
        st = tiering.current_status()
        # Snapshot B is queued behind the stuck A: its bytes are lag.
        assert st.get("queued_lag_bytes", 0) > 0
        assert st["lag_bytes"] >= st["queued_lag_bytes"]
        gate.set()
        assert mgr.wait_idle(timeout=60)
        st = tiering.current_status()
        assert st["lag_bytes"] == 0 and st.get("queued_lag_bytes", 0) == 0
    finally:
        unregister_storage_plugin("gatedfs")


# ------------------------------------------------------- outage tolerance


@pytest.mark.chaos
def test_outage_take_never_blocks_and_lag_recovers(tmp_path, monkeypatch):
    """The acceptance soak, shrunk: remote down for a window — the
    tiered take's wall stays within 1.5x of a plain local take (+ a
    small absolute floor for fixed per-take overhead at this tiny
    size), the drain degrades (lag gauge > 0, degraded episode
    counted), then recovers to remote-durable with lag 0."""
    monkeypatch.setenv("TPUSNAP_TIER_DRAIN", "1")
    state = _state()
    t0 = time.monotonic()
    Snapshot.take(str(tmp_path / "plain"), state)
    plain_wall = time.monotonic() - t0

    url, remote_dir = _tier_url(tmp_path, remote_scheme="chaos+fs")
    opts = {"fault_plan": FaultPlan(outage=("*", 0.0, 1.2))}
    before = telemetry.global_counters_snapshot().get(
        "tier.degraded_episodes", 0
    )
    with knobs.override_tier_outage(
        threshold=1, backoff_cap_s=0.1, op_deadline_s=0.1
    ):
        t0 = time.monotonic()
        Snapshot.take(url, state, storage_options=opts)
        tier_wall = time.monotonic() - t0
        assert tier_wall <= max(plain_wall * 1.5, plain_wall + 0.5), (
            f"tiered take blocked on the outage: {tier_wall:.2f}s vs "
            f"plain {plain_wall:.2f}s"
        )
        # Lag is visible while the outage holds the drain back.
        deadline = time.monotonic() + 10
        saw_lag = False
        while time.monotonic() < deadline:
            st = tiering.read_tier_status()
            if st and (st.get("lag_bytes") or 0) > 0:
                saw_lag = True
                break
            time.sleep(0.02)
        assert saw_lag, "upload lag never surfaced during the outage"
        # ...and falls to zero once the window passes.
        assert tiering.drain_manager().wait_idle(timeout=30)
    st = tiering.read_tier_status()
    assert st["state"] == "durable" and st["lag_bytes"] == 0
    after = telemetry.global_counters_snapshot().get(
        "tier.degraded_episodes", 0
    )
    assert after > before
    assert fsck_snapshot(parse_tier_url(url).local_dir).durability == (
        "remote-durable"
    )
    restored = _zeros()
    Snapshot(remote_dir).restore(restored)
    _assert_eq(state, restored)


@pytest.mark.slow
@pytest.mark.chaos
def test_outage_take_local_speed_2gb(tmp_path, monkeypatch):
    """The acceptance criterion at full scale: a 2 GB tiered take
    against an unavailable remote commits within 1.5x of a plain local
    take."""
    monkeypatch.setenv("TPUSNAP_TIER_DRAIN", "0")
    big = {
        "m": StateDict(
            **{
                f"w{i}": np.random.default_rng(i)
                .standard_normal((128, 1024, 1024))
                .astype(np.float32)
                for i in range(4)
            }
        )
    }  # 4 x 512 MB
    t0 = time.monotonic()
    Snapshot.take(str(tmp_path / "plain"), big)
    plain_wall = time.monotonic() - t0

    url, _ = _tier_url(tmp_path, remote_scheme="chaos+fs")
    opts = {"fault_plan": FaultPlan(outage=("*", 0.0, 3600.0))}
    t0 = time.monotonic()
    Snapshot.take(url, big, storage_options=opts)
    tier_wall = time.monotonic() - t0
    assert tier_wall <= plain_wall * 1.5, (
        f"2GB tiered take blocked on the outage: {tier_wall:.2f}s vs "
        f"plain {plain_wall:.2f}s"
    )
    assert (
        fsck_snapshot(parse_tier_url(url).local_dir).durability
        == "local-committed"
    )


# ------------------------------------------------------------ crash matrix


_DRAIN_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["TPUSNAP_TIER_DRAIN"] = "0"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict, tiering

url, kill_after = sys.argv[1], int(sys.argv[2])
state = {"m": StateDict(**{
    f"w{i}": np.random.default_rng(i).standard_normal((64, 64)).astype(np.float32)
    for i in range(6)})}
from tpusnap.knobs import override_batching_disabled
with override_batching_disabled(True):
    Snapshot.take(url, state)
print("TAKEN", flush=True)
# Chaos remote SIGKILLs this process right after the Nth successful
# payload write — mid-upload-drain, deterministic.
os.environ["TPUSNAP_FAULT_SPEC"] = f"crash_after_op=write:{kill_after}"
spec = tiering.parse_tier_url(url)
tiering.drain_snapshot(url, remote_url="chaos+" + spec.remote_url)
print("DRAINED (kill overshot)", flush=True)
"""


def test_sigkill_mid_drain_resume_skips_half(tmp_path):
    """Crash-matrix window (a): SIGKILL mid-upload-drain. fsck says
    local-committed; the restarted drain converges to remote-durable
    with ≥50% of the upload bytes skipped on journal evidence."""
    url, remote_dir = _tier_url(tmp_path)
    kill_after = 4  # of 6 single-array blobs
    r = subprocess.run(
        [sys.executable, "-c", _DRAIN_CHILD, url, str(kill_after)],
        capture_output=True,
        text=True,
        env={**os.environ, "TPUSNAP_TELEMETRY_DIR": str(tmp_path / "tele_c")},
        timeout=180,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == -signal.SIGKILL, r.stdout + r.stderr
    assert "TAKEN" in r.stdout

    local_dir = parse_tier_url(url).local_dir
    rep = fsck_snapshot(local_dir)
    assert rep.state == "committed"
    assert rep.durability == "local-committed"
    # No remote metadata: the remote tier never half-commits.
    assert not os.path.exists(os.path.join(remote_dir, ".snapshot_metadata"))
    journal = read_upload_journal_dir(local_dir)
    assert journal["state"] == "pending"
    # Evidence for at least the pre-kill blobs minus the in-flight one.
    assert len(journal["blobs"]) >= kill_after - 1

    resumed = drain_snapshot(url)
    assert resumed.state == "durable"
    total = resumed.bytes_skipped + resumed.bytes_uploaded
    assert resumed.bytes_skipped >= total * 0.5, resumed.summary()
    restored = _zeros()
    Snapshot(remote_dir).restore(restored)
    _assert_eq(_state(), restored)


_GC_CHILD = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
local_dir, kill_after = sys.argv[1], int(sys.argv[2])
os.environ["TPUSNAP_FAULT_SPEC"] = f"crash_after_op=delete:{kill_after}"
from tpusnap.lifecycle import gc_snapshot
print("MARK", flush=True)
gc_snapshot("chaos+fs://" + local_dir, dry_run=False, evict_local=True)
print("EVICTED (kill overshot)", flush=True)
"""


def test_sigkill_mid_evict_remote_stays_restorable(tmp_path):
    """Crash-matrix window (b): SIGKILL mid-gc of drained local blobs.
    The remote-durable snapshot stays restorable from the remote, and
    the local dir keeps classifying remote-durable (partial eviction =
    evicted blobs, never 'missing')."""
    url, remote_dir = _tier_url(tmp_path)
    with knobs.override_batching_disabled(True):
        Snapshot.take(url, _state())
    assert drain_snapshot(url).state == "durable"
    local_dir = parse_tier_url(url).local_dir

    r = subprocess.run(
        [sys.executable, "-c", _GC_CHILD, local_dir, "2"],
        capture_output=True,
        text=True,
        env={**os.environ, "TPUSNAP_TELEMETRY_DIR": str(tmp_path / "tele_c")},
        timeout=180,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == -signal.SIGKILL, r.stdout + r.stderr

    rep = fsck_snapshot(local_dir)
    assert rep.state == "committed"
    assert rep.durability == "remote-durable"
    assert rep.evicted and not rep.missing_referenced
    # Restorable from the remote, bit-exact — and through the tier URL
    # (per-blob fallback over the half-evicted cache).
    for path in (remote_dir, url):
        restored = _zeros()
        Snapshot(path).restore(restored)
        _assert_eq(_state(), restored)


# ----------------------------------------------------------- gc eviction


def test_evict_refused_before_durable_and_within_retention(tmp_path):
    url, _ = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir
    with pytest.raises(RuntimeError, match="NOT yet proven remote"):
        gc_snapshot(local_dir, dry_run=False, evict_local=True)

    assert drain_snapshot(url).state == "durable"
    with knobs.override_tier_outage(local_retention_s=3600):
        with pytest.raises(RuntimeError, match="hot local cache"):
            gc_snapshot(local_dir, dry_run=False, evict_local=True)

    report = gc_snapshot(local_dir, dry_run=False, evict_local=True)
    assert report.bytes_reclaimed > 0
    rep = fsck_snapshot(local_dir)
    assert rep.durability == "remote-durable"
    assert rep.evicted and not rep.missing_referenced
    restored = _zeros()
    Snapshot(url).restore(restored)  # read-through after eviction
    _assert_eq(_state(), restored)
    assert (
        telemetry.global_counters_snapshot().get("tier.remote_fallback_reads", 0)
        > 0
    )


def test_evict_via_tier_url_never_touches_remote(tmp_path):
    url, remote_dir = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    assert drain_snapshot(url).state == "durable"
    gc_snapshot(url, dry_run=False, evict_local=True)
    # The remote copy is intact (eviction rewrote the path to local).
    restored = _zeros()
    Snapshot(remote_dir).restore(restored)
    _assert_eq(_state(), restored)


# ------------------------------------------------------------- CLI legs


def _cli(*args, env=None):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", *args],
        capture_output=True,
        text=True,
        env={**os.environ, **(env or {})},
        timeout=180,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def test_drain_cli_exit_contract(tmp_path):
    url, _ = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir

    r = _cli("drain", local_dir, "--status")
    assert r.returncode == 2  # tiered but not yet durable
    assert "local-committed" in r.stdout

    r = _cli("drain", local_dir)  # journal names the remote
    assert r.returncode == 0, r.stdout + r.stderr
    assert "durable" in r.stdout

    r = _cli("drain", local_dir, "--status", "--json")
    assert r.returncode == 0
    st = json.loads(r.stdout)
    assert st["durability"] == "remote-durable" and st["lag_bytes"] == 0

    r = _cli("drain", str(tmp_path / "not_tiered"))
    assert r.returncode == 3


def test_fsck_cli_shows_durability(tmp_path):
    url, _ = _tier_url(tmp_path)
    Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir
    r = _cli("fsck", local_dir)
    assert r.returncode == 0
    assert "local-committed" in r.stdout
    drain_snapshot(url)
    r = _cli("fsck", local_dir)
    assert "remote-durable" in r.stdout


# ------------------------------------------------- outage fault (faults.py)


@pytest.mark.chaos
class TestOutageFault:
    def test_spec_parse(self):
        p = FaultPlan.from_spec("outage=write:10")
        assert p.outage == ("write", 0.0, 10.0)
        p = FaultPlan.from_spec("outage=*:5:10")
        assert p.outage == ("*", 5.0, 10.0)
        with pytest.raises(ValueError):
            FaultPlan.from_spec("outage=10")

    def test_window_is_deterministic(self, tmp_path, monkeypatch):
        from tpusnap import faults as faults_mod
        from tpusnap.faults import (
            FaultInjectionStoragePlugin,
            InjectedFaultError,
        )
        from tpusnap.storage_plugins.fs import FSStoragePlugin

        clock = [100.0]
        monkeypatch.setattr(faults_mod, "_mono", lambda: clock[0])
        plugin = FaultInjectionStoragePlugin(
            FSStoragePlugin(root=str(tmp_path / "d")),
            FaultPlan(outage=("write", 2.0, 5.0)),
        )
        # t=0 (anchor): before the window — op succeeds.
        plugin.sync_write(WriteIO(path="a", buf=b"1"))
        clock[0] += 3.0  # t=3: inside [2, 7)
        with pytest.raises(InjectedFaultError, match="outage"):
            plugin.sync_write(WriteIO(path="b", buf=b"2"))
        # Reads are untouched (kind filter).
        rio = ReadIO(path="a")
        plugin.sync_read(rio)
        assert rio.buf.getvalue() == b"1"
        clock[0] += 5.0  # t=8: window over
        plugin.sync_write(WriteIO(path="b", buf=b"2"))
        counters = telemetry.global_counters_snapshot()
        assert counters.get("faults.outage.write", 0) >= 1


# ------------------------------------- retry-budget exhaustion (retry.py)


class _AlwaysDown(StoragePlugin):
    async def write(self, write_io):
        raise ConnectionError("down")

    async def read(self, read_io):
        raise ConnectionError("down")

    async def delete(self, path):
        raise ConnectionError("down")


def test_retry_exhaustion_counter_and_flight_event():
    from tpusnap import flight
    from tpusnap.retry import RetryingStoragePlugin, RetryPolicy

    flight.reset_for_tests()
    plugin = RetryingStoragePlugin(
        _AlwaysDown(),
        RetryPolicy(deadline_sec=0.0, backoff_base_sec=0.001),
    )
    before = telemetry.global_counters_snapshot().get(
        "retry.exhausted.write", 0
    )
    with pytest.raises(ConnectionError):
        plugin.sync_write(WriteIO(path="blob/x", buf=b"z"))
    after = telemetry.global_counters_snapshot().get("retry.exhausted.write", 0)
    assert after == before + 1
    events = [
        e
        for e in flight.recorder().snapshot_events()
        if e.get("k") == "retry_exhausted"
    ]
    assert events, "no retry_exhausted flight breadcrumb"
    ev = events[-1]
    assert ev["op"] == "write" and ev["path"] == "blob/x"
    assert ev["error"] == "ConnectionError"


def test_hard_fatal_still_counts_fatal():
    from tpusnap.retry import RetryingStoragePlugin, RetryPolicy

    class _Denied(StoragePlugin):
        async def write(self, write_io):
            raise PermissionError(13, "nope")

        async def read(self, read_io):
            raise PermissionError(13, "nope")

        async def delete(self, path):
            raise PermissionError(13, "nope")

    plugin = RetryingStoragePlugin(_Denied(), RetryPolicy(deadline_sec=60.0))
    before = telemetry.global_counters_snapshot()
    with pytest.raises(PermissionError):
        plugin.sync_write(WriteIO(path="blob/y", buf=b"z"))
    after = telemetry.global_counters_snapshot()
    assert after.get("retry.fatal.write", 0) == before.get(
        "retry.fatal.write", 0
    ) + 1
    assert after.get("retry.exhausted.write", 0) == before.get(
        "retry.exhausted.write", 0
    )


# ------------------------------------------------- tier-aware RTO (slo.py)


def _restore_events(n, plugin, gbps):
    return [
        {
            "kind": "restore",
            "rank": 0,
            "bytes": 1_000_000_000,
            "wall_s": 1.0 / gbps,
            "plugin": plugin,
            "phases_s": {"restore.read": 1.0 / gbps},
        }
        for _ in range(n)
    ]


def test_estimate_rto_backend_filter():
    from tpusnap.slo import estimate_rto

    events = _restore_events(5, "FSStoragePlugin", 4.0) + _restore_events(
        5, "S3StoragePlugin", 0.25
    )
    local = estimate_rto(10_000_000_000, events, backend="FSStoragePlugin")
    remote = estimate_rto(10_000_000_000, events, backend="S3StoragePlugin")
    assert local.ok and remote.ok
    # 4 GB/s local vs 0.25 GB/s cloud: the tier must change the answer.
    assert remote.seconds > local.seconds * 10
    missing = estimate_rto(1, events, backend="GCSStoragePlugin")
    assert not missing.ok and "GCSStoragePlugin" in missing.reason


def test_restore_source_label_tracks_eviction(tmp_path):
    # Not tiered → no filter.
    assert restore_source_label(str(tmp_path)) is None
    url = f"tier+local={tmp_path / 'cache'}+remote=fs://{tmp_path / 'remote'}/s"
    Snapshot.take(url, _state())
    local_dir = parse_tier_url(url).local_dir
    # Cached → local tier label (both via URL and via the local dir).
    assert restore_source_label(url) == "FSStoragePlugin"
    assert restore_source_label(local_dir) == "FSStoragePlugin"
    drain_snapshot(url)
    gc_snapshot(local_dir, dry_run=False, evict_local=True)
    # Evicted → a restore reads the remote tier.
    # (remote scheme fs here; the label logic keys off cache state)
    journal = read_upload_journal_dir(local_dir)
    assert journal["state"] == "durable"
    assert restore_source_label(url) == "FSStoragePlugin"  # fs remote

    # Pretend the remote is s3 (label map leg, no client needed).
    journal["remote"] = "s3://bucket/s"
    with open(os.path.join(local_dir, UPLOAD_JOURNAL_PATH), "w") as f:
        json.dump(journal, f)
    assert restore_source_label(local_dir) == "S3StoragePlugin"


def test_restore_history_event_carries_plugin_label(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAP_HISTORY", "1")
    path = str(tmp_path / "plain")
    state = _state()
    Snapshot.take(path, state)
    restored = _zeros()
    Snapshot(path).restore(restored)
    from tpusnap.history import load_history

    events = [
        e
        for e in load_history()
        if e.get("kind") == "restore" and e.get("path") == path
    ]
    assert events and events[-1].get("plugin") == "FSStoragePlugin"


# ------------------------------------------------------- metrics export


def test_prom_sink_exports_tier_gauges_and_exhausted_family(tmp_path):
    from tpusnap.metrics_export import (
        PrometheusTextfileSink,
        parse_prometheus_textfile,
    )

    sink = PrometheusTextfileSink(str(tmp_path / "prom"))
    sink.on_tier_update(
        {
            "state": "degraded",
            "lag_bytes": 12345,
            "lag_seconds": 6.5,
            "degraded": True,
        }
    )
    telemetry.incr("retry.exhausted.write")
    text = sink.render()
    metrics = parse_prometheus_textfile(text)
    from tpusnap.knobs import get_job_id

    assert metrics["tpusnap_upload_lag_bytes"]["samples"] == {
        f'{{job="{get_job_id()}",rank="0"}}': 12345.0
    }
    assert list(metrics["tpusnap_upload_lag_seconds"]["samples"].values()) == [
        6.5
    ]
    assert list(metrics["tpusnap_tier_degraded"]["samples"].values()) == [1.0]
    assert any(
        "exhausted.write" in labels
        for labels in metrics["tpusnap_retry_total"]["samples"]
    )


def test_drain_report_json_roundtrip():
    r = DrainReport(local_dir="/a", remote_url="fs:///b", state="durable")
    r.bases.append(
        DrainReport(local_dir="/base", remote_url="fs:///c", state="durable")
    )
    d = r.to_json()
    assert d["state"] == "durable" and d["bases"][0]["local_dir"] == "/base"
