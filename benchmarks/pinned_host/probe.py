"""pinned_host (UVM-analog) probe — run on the real chip by bench.py.

Creates a ``memory_kind="pinned_host"`` array on the default backend
(the real TPU when the driver runs the bench), snapshots it, restores it
into a pinned_host target, and reports whether the memory kind survived
the round trip — the on-hardware proof of the host-offload capability
(reference uvm_tensor.py:24-39 + tests/gpu_tests/test_torchrec.py:181-262
prove theirs on GPU). Deliberately tiny (4 MB): this environment's
PJRT tunnel moves ~10 MB/s device->host, and the probe measures
capability, not bandwidth. Prints ONE JSON line; never raises (the
caller treats a hang via subprocess timeout — the tunnel is known to
wedge for minutes).
"""

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(
    0,
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
)


def main() -> None:
    out = {"ok": False}
    work = None
    try:
        # Honor JAX_PLATFORMS if the caller set one (local CPU testing);
        # default — the driver's bench run — is the real chip.
        from tpusnap.test_utils import apply_platform_env

        apply_platform_env()
        import jax
        import jax.numpy as jnp
        import numpy as np

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        from tpusnap.host_offload import (
            is_host_resident,
            supports_host_offload,
            to_host_offload,
        )

        if not supports_host_offload(dev):
            out["error"] = "backend lacks host memory kinds"
            return
        n = 1 << 20  # 4 MB of f32
        arr = jax.device_put(jnp.arange(n, dtype=jnp.float32), dev)
        offloaded = to_host_offload(arr)
        out["host_resident"] = bool(is_host_resident(offloaded))

        from tpusnap import PytreeState, Snapshot

        work = tempfile.mkdtemp(prefix="tpusnap_phprobe_")
        snap = work + "/snap"
        Snapshot.take(snap, {"m": PytreeState({"table": offloaded})})
        target = {
            "m": PytreeState(
                {
                    "table": to_host_offload(
                        jax.device_put(jnp.zeros(n, jnp.float32), dev)
                    )
                }
            )
        }
        Snapshot(snap).restore(target)
        restored = target["m"].tree["table"]
        out["restored_memory_kind"] = getattr(
            restored.sharding, "memory_kind", None
        )
        out["values_equal"] = bool(
            np.array_equal(np.asarray(restored), np.asarray(arr))
        )
        out["ok"] = (
            out["values_equal"]
            and out["restored_memory_kind"] == "pinned_host"
        )
    except Exception as e:  # noqa: BLE001 - report, never crash the bench
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        if work:
            shutil.rmtree(work, ignore_errors=True)
        print(json.dumps(out))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
