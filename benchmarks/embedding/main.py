"""Embedding-table (torchrec-analog) snapshot benchmark: sync vs async
take of a sharded embedding collection, with RSS tracking.

Mirrors /root/reference/benchmarks/torchrec/main.py:133-151,211-231
(row-wise DLRM tables, sync-vs-async blocked-time split, RSS deltas
validating the memory budget). Tables are row-wise sharded over the
mesh's model axes; the async variant reports the *blocked* time (until
``async_take`` returns — training could resume here) separately from the
total time (until the background I/O drains).

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/embedding/main.py [--rows 1000000]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from tpusnap.test_utils import apply_platform_env

apply_platform_env()

import jax

from tpusnap import PytreeState, Snapshot
from tpusnap.models import EmbeddingCollection, TableConfig, make_mesh
from tpusnap.rss_profiler import measure_rss_deltas


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=1_000_000)
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--tables", type=int, default=4)
    args = parser.parse_args()

    mesh = make_mesh()
    model = EmbeddingCollection(
        [
            TableConfig(f"table_{i}", args.rows, args.dim, sharding="row")
            for i in range(args.tables)
        ]
    )
    params = model.shard_params(model.init(jax.random.PRNGKey(0)), mesh)
    nbytes = sum(leaf.nbytes for leaf in jax.tree.leaves(params))
    print(
        f"{args.tables} tables x [{args.rows}, {args.dim}] row-wise "
        f"(+ rowwise-adagrad state): {nbytes / 1e9:.2f} GB "
        f"over mesh {dict(mesh.shape)}"
    )

    with tempfile.TemporaryDirectory(prefix="tpusnap_bench_emb_") as work:
        # Warm-up: the first take jit-compiles the device slab-pack
        # program (one-time per slab composition); timing it against the
        # warm async path below would misattribute compile time to the
        # sync pipeline.
        Snapshot.take(os.path.join(work, "warmup"), {"emb": PytreeState(params)})
        os.sync()

        rss_deltas = []
        with measure_rss_deltas(rss_deltas):
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(work, "sync"), {"emb": PytreeState(params)})
            sync_s = time.perf_counter() - t0
        print(
            f"sync take:  {sync_s:.2f}s ({nbytes / sync_s / 1e9:.2f} GB/s), "
            f"peak RSS delta {max(rss_deltas) / 1e6:.0f} MB"
        )

        t0 = time.perf_counter()
        pending = Snapshot.async_take(
            os.path.join(work, "async"), {"emb": PytreeState(params)}
        )
        blocked_s = time.perf_counter() - t0
        pending.wait()
        total_s = time.perf_counter() - t0
        print(
            f"async take: blocked {blocked_s:.2f}s / total {total_s:.2f}s "
            f"(training stalls {blocked_s / total_s:.0%} of the snapshot)"
        )

        target = PytreeState(params)
        t0 = time.perf_counter()
        Snapshot(os.path.join(work, "sync")).restore({"emb": target})
        restore_s = time.perf_counter() - t0
        print(f"restore:    {restore_s:.2f}s ({nbytes / restore_s / 1e9:.2f} GB/s)")


if __name__ == "__main__":
    main()
