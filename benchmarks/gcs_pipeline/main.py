"""GCS-plugin full-pipeline benchmark against the fake server, with
injected per-request latency.

The north-star production target is GCS (BASELINE.md; the reference
publishes network-storage rows next to local FS,
/root/reference/benchmarks/ddp/README.md:21-24). Real-bucket CI needs
credentials this environment does not have, so this harness measures
the part of cloud throughput the FRAMEWORK controls — how many
requests the pipeline keeps in flight — against the same fake GCS
server the fault-matrix tests use (tests/test_gcs.py), with a fixed
latency injected into EVERY request (simulating cloud RTT; loopback
bandwidth is effectively infinite, so latency-hiding is the whole
game, exactly as it is against a real bucket from a TPU VM).

Reported per phase (take / restore):

- wall seconds and effective GB/s through the FULL pipeline
  (Snapshot.take / restore with slab batching, resumable-upload
  chunking, ranged downloads);
- requests issued and the serial floor (requests x latency): what a
  one-request-at-a-time client would need for latency alone;
- concurrency = serial floor / wall — the latency-hiding factor the
  scheduler + plugin achieve end to end.

Run:
    JAX_PLATFORMS=cpu python benchmarks/gcs_pipeline/main.py \
        [--latency-ms 30] [--total-mb 256]
"""

import argparse
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

from tpusnap.test_utils import apply_platform_env

apply_platform_env()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--latency-ms", type=float, default=100.0)
    parser.add_argument("--total-mb", type=int, default=256)
    parser.add_argument(
        "--upload-chunk-mb",
        type=int,
        default=8,
        help="resumable-upload chunk size (production default is 100 MB; "
        "smaller here so a modest state still exercises multi-chunk "
        "sessions)",
    )
    args = parser.parse_args()

    from http.server import ThreadingHTTPServer

    import numpy as np

    import tpusnap.storage_plugins.gcs as gcs_mod
    from test_gcs import FakeGCS, _make_handler  # the fault-matrix fake
    from tpusnap import PytreeState, Snapshot
    from tpusnap.knobs import override_slab_size_threshold_bytes

    state_srv = FakeGCS()
    server = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(state_srv))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    endpoint = f"http://127.0.0.1:{server.server_address[1]}"

    chunk = args.upload_chunk_mb << 20
    prev_up, prev_down = gcs_mod._UPLOAD_CHUNK_SIZE, gcs_mod._DOWNLOAD_CHUNK_SIZE
    gcs_mod._UPLOAD_CHUNK_SIZE = chunk
    gcs_mod._DOWNLOAD_CHUNK_SIZE = chunk

    total = args.total_mb << 20
    rng = np.random.default_rng(0)
    # Mixed shape census like a real train state: a few large arrays
    # (multi-chunk resumable sessions) + many small ones (slab-batched
    # into a handful of uploads — the reason cloud stores need slabs).
    big = {
        f"big{i}": rng.integers(0, 255, total // 8, dtype=np.uint8)
        for i in range(6)
    }
    small = {
        f"small{i}": rng.integers(0, 255, 64 << 10, dtype=np.uint8)
        for i in range(64)
    }
    state = {**big, **small}
    nbytes = sum(a.nbytes for a in state.values())
    opts = {"api_endpoint": endpoint, "deadline_sec": 120.0}
    lat = args.latency_ms / 1e3

    def phase(name, fn):
        state_srv.request_log.clear()
        t0 = time.perf_counter()
        fn()
        wall = time.perf_counter() - t0
        reqs = len(state_srv.request_log)
        serial_floor = reqs * lat
        print(
            f"{name:8s} {wall:6.2f}s  {nbytes / wall / 1e9:5.2f} GB/s  "
            f"{reqs:4d} requests, serial latency floor "
            f"{serial_floor:6.2f}s -> concurrency {serial_floor / wall:4.1f}x"
        )
        return wall

    # The whole harness is a ~1/16-scale model of the production cloud
    # shape census: upload chunks 8 MB (prod 100 MB), slab threshold
    # 2 MB (prod 128 MB) — so the large arrays are standalone objects
    # whose resumable sessions upload IN PARALLEL (chunks within one
    # session are protocol-sequential), and the small arrays still
    # batch into a handful of slab objects.
    try:
        print(
            f"state: {nbytes / 1e6:.0f} MB ({len(big)} large + {len(small)} "
            f"small arrays), latency {args.latency_ms:.0f} ms/request, "
            f"upload/download chunk {args.upload_chunk_mb} MB"
        )
        state_srv.latency_s = lat
        with override_slab_size_threshold_bytes(2 << 20):
            phase(
                "take",
                lambda: Snapshot.take(
                    "gs://bkt/snap",
                    {"m": PytreeState(state)},
                    storage_options=opts,
                ),
            )

            target = {
                "m": PytreeState(
                    {k: np.zeros_like(v) for k, v in state.items()}
                )
            }
            phase(
                "restore",
                lambda: Snapshot(
                    "gs://bkt/snap", storage_options=opts
                ).restore(target),
            )
        ok = all(
            np.array_equal(target["m"].tree[k], v) for k, v in state.items()
        )
        print(f"restore verified: {ok}")
        if not ok:
            raise SystemExit(1)
    finally:
        gcs_mod._UPLOAD_CHUNK_SIZE = prev_up
        gcs_mod._DOWNLOAD_CHUNK_SIZE = prev_down
        server.shutdown()


if __name__ == "__main__":
    main()
