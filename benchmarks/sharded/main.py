"""Sharded (FSDP-analog) snapshot benchmark: save + restore a mesh-
sharded transformer train state.

Mirrors /root/reference/benchmarks/fsdp/main.py:35-104 (1.9B-param
nn.Transformer under LOCAL_STATE_DICT): the state is genuinely
partitioned — each shard written once by its owner — and restore puts
every shard back onto its device with the target sharding.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/sharded/main.py [--d-model 1024]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from tpusnap.test_utils import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp

from tpusnap import PytreeState, Snapshot
from tpusnap.models import Transformer, TransformerConfig, make_mesh
from tpusnap.models.transformer import init_train_state


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument(
        "--runs",
        type=int,
        default=3,
        help="samples per phase; the virtio disk swings >2x minute to "
        "minute, so best-of-N is the repeatable number",
    )
    args = parser.parse_args()

    mesh = make_mesh()
    cfg = TransformerConfig(
        vocab_size=32768,
        d_model=args.d_model,
        n_heads=16,
        n_layers=args.n_layers,
        d_ff=4 * args.d_model,
    )
    model = Transformer(cfg)
    state = init_train_state(model, mesh, jax.random.PRNGKey(0))
    nbytes = sum(leaf.nbytes for leaf in jax.tree.leaves(state))
    print(f"train state: {nbytes / 1e9:.2f} GB over mesh {dict(mesh.shape)}")

    take_runs, restore_runs = [], []
    with tempfile.TemporaryDirectory(prefix="tpusnap_bench_shard_") as work_dir:
        for run in range(args.runs):
            path = os.path.join(work_dir, f"snap{run}")
            os.sync()
            t0 = time.perf_counter()
            Snapshot.take(path, {"ts": PytreeState(state)})
            take_runs.append(time.perf_counter() - t0)

            target = PytreeState(jax.tree.map(jnp.zeros_like, state))
            t0 = time.perf_counter()
            Snapshot(path).restore({"ts": target})
            restore_runs.append(time.perf_counter() - t0)

    take_s, restore_s = min(take_runs), min(restore_runs)
    print(f"take:    {take_s:.2f}s ({nbytes / take_s / 1e9:.2f} GB/s) "
          f"runs={[round(t, 2) for t in take_runs]}")
    print(f"restore: {restore_s:.2f}s ({nbytes / restore_s / 1e9:.2f} GB/s) "
          f"runs={[round(t, 2) for t in restore_runs]}")


if __name__ == "__main__":
    main()
