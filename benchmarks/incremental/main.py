"""Incremental snapshot + integrity scrub benchmark.

No reference counterpart (torchsnapshot rewrites every byte every take
and cannot detect corruption). Simulates the common training shape: a
large mostly-frozen component (embeddings / frozen tower) plus a small
hot component that changes every step. Reports, best-of-N:

- full take of the whole state (the baseline every checkpoint pays
  without dedup),
- incremental take after the hot component changed (only it rewrites),
- bytes on disk for the increment vs the full snapshot,
- scrub throughput (``verify_snapshot`` over the full snapshot).

Run: python benchmarks/incremental/main.py [--gb 2.0] [--hot-mb 64]
"""

import argparse
import glob
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def du(path: str) -> int:
    return sum(
        os.path.getsize(f)
        for f in glob.glob(os.path.join(path, "**", "*"), recursive=True)
        if os.path.isfile(f)
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=2.0)
    parser.add_argument("--hot-mb", type=float, default=64.0)
    parser.add_argument("--runs", type=int, default=3)
    args = parser.parse_args()

    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot

    frozen_nbytes = int(args.gb * 1024**3)
    hot_nbytes = int(args.hot_mb * 1024**2)
    rng = np.random.default_rng(0)
    frozen = rng.integers(0, 2**16, frozen_nbytes // 2, dtype=np.uint16).reshape(
        -1, 4096
    )
    hot = rng.standard_normal(hot_nbytes // 4).astype(np.float32)
    total_gb = (frozen.nbytes + hot.nbytes) / 1e9
    print(
        f"state: {total_gb:.2f} GB ({frozen.nbytes / 1e9:.2f} frozen + "
        f"{hot.nbytes / 1e6:.0f} MB hot)"
    )

    root = tempfile.mkdtemp(prefix="tpusnap_inc_bench_")
    try:
        full_times, inc_times = [], []
        for run in range(args.runs):
            base = os.path.join(root, f"base{run}")
            inc = os.path.join(root, f"inc{run}")
            state = {"app": StateDict(frozen=frozen, hot=hot)}
            t0 = time.perf_counter()
            Snapshot.take(base, state)
            full_times.append(time.perf_counter() - t0)

            hot2 = hot + np.float32(run + 1)
            t0 = time.perf_counter()
            Snapshot.take(
                inc,
                {"app": StateDict(frozen=frozen, hot=hot2)},
                incremental_from=base,
            )
            inc_times.append(time.perf_counter() - t0)
            inc_bytes, base_bytes = du(inc), du(base)
            if run + 1 < args.runs:
                shutil.rmtree(base)
                shutil.rmtree(inc)

        t_full, t_inc = min(full_times), min(inc_times)
        print(
            f"full take:        {t_full:.2f}s ({total_gb / t_full:.2f} GB/s) "
            f"runs={[round(t, 2) for t in full_times]}"
        )
        print(
            f"incremental take: {t_inc:.2f}s ({total_gb / t_inc:.2f} GB/s "
            f"effective, {t_full / t_inc:.1f}x) "
            f"runs={[round(t, 2) for t in inc_times]}"
        )
        print(
            f"bytes on disk:    full {base_bytes / 1e9:.2f} GB, "
            f"increment {inc_bytes / 1e6:.1f} MB "
            f"({base_bytes / max(inc_bytes, 1):.0f}x smaller)"
        )

        scrub_times = []
        for _ in range(args.runs):
            t0 = time.perf_counter()
            report = verify_snapshot(base)
            scrub_times.append(time.perf_counter() - t0)
            assert report.clean, report.summary()
        t_scrub = min(scrub_times)
        print(
            f"scrub (verify):   {t_scrub:.2f}s ({total_gb / t_scrub:.2f} GB/s) "
            f"runs={[round(t, 2) for t in scrub_times]}"
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
