"""Incremental snapshot + integrity scrub benchmark.

No reference counterpart (torchsnapshot rewrites every byte every take
and cannot detect corruption). Simulates the common training shape: a
large mostly-frozen component (embeddings / frozen tower) plus a small
hot component that changes every step. Reports, best-of-N:

- full take of the whole state (the baseline every checkpoint pays
  without dedup),
- incremental take after the hot component changed (only it rewrites),
- bytes on disk for the increment vs the full snapshot,
- scrub throughput (``verify_snapshot`` over the full snapshot).

Run: python benchmarks/incremental/main.py [--gb 2.0] [--hot-mb 64]
"""

import argparse
import glob
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def du(path: str) -> int:
    return sum(
        os.path.getsize(f)
        for f in glob.glob(os.path.join(path, "**", "*"), recursive=True)
        if os.path.isfile(f)
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=2.0)
    parser.add_argument("--hot-mb", type=float, default=64.0)
    parser.add_argument("--runs", type=int, default=3)
    parser.add_argument(
        "--chain-depth",
        type=int,
        default=100,
        help="depth of the incremental-chain sweep (0 disables)",
    )
    args = parser.parse_args()

    import numpy as np

    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.knobs import override_record_dedup_hashes

    frozen_nbytes = int(args.gb * 1024**3)
    hot_nbytes = int(args.hot_mb * 1024**2)
    rng = np.random.default_rng(0)
    frozen = rng.integers(0, 2**16, frozen_nbytes // 2, dtype=np.uint16).reshape(
        -1, 4096
    )
    hot = rng.standard_normal(hot_nbytes // 4).astype(np.float32)
    total_gb = (frozen.nbytes + hot.nbytes) / 1e9
    print(
        f"state: {total_gb:.2f} GB ({frozen.nbytes / 1e9:.2f} frozen + "
        f"{hot.nbytes / 1e6:.0f} MB hot)"
    )

    root = tempfile.mkdtemp(prefix="tpusnap_inc_bench_")
    try:
        full_times, inc_times = [], []
        for run in range(args.runs):
            base = os.path.join(root, f"base{run}")
            inc = os.path.join(root, f"inc{run}")
            state = {"app": StateDict(frozen=frozen, hot=hot)}
            t0 = time.perf_counter()
            # Bases of planned incremental chains record 64-bit dedup
            # hashes (TPUSNAP_RECORD_DEDUP_HASHES — the documented
            # production pattern): every skip decision then has 64-bit
            # evidence from the FIRST increment. A plain base
            # conservatively rewrites once instead.
            with override_record_dedup_hashes(True):
                Snapshot.take(base, state)
            full_times.append(time.perf_counter() - t0)

            hot2 = hot + np.float32(run + 1)
            t0 = time.perf_counter()
            Snapshot.take(
                inc,
                {"app": StateDict(frozen=frozen, hot=hot2)},
                incremental_from=base,
            )
            inc_times.append(time.perf_counter() - t0)
            inc_bytes, base_bytes = du(inc), du(base)
            if run + 1 < args.runs:
                shutil.rmtree(base)
                shutil.rmtree(inc)

        t_full, t_inc = min(full_times), min(inc_times)
        print(
            f"full take:        {t_full:.2f}s ({total_gb / t_full:.2f} GB/s) "
            f"runs={[round(t, 2) for t in full_times]}"
        )
        print(
            f"incremental take: {t_inc:.2f}s ({total_gb / t_inc:.2f} GB/s "
            f"effective, {t_full / t_inc:.1f}x) "
            f"runs={[round(t, 2) for t in inc_times]}"
        )
        print(
            f"bytes on disk:    full {base_bytes / 1e9:.2f} GB, "
            f"increment {inc_bytes / 1e6:.1f} MB "
            f"({base_bytes / max(inc_bytes, 1):.0f}x smaller)"
        )

        scrub_times = []
        for _ in range(args.runs):
            t0 = time.perf_counter()
            report = verify_snapshot(base)
            scrub_times.append(time.perf_counter() - t0)
            assert report.clean, report.summary()
        t_scrub = min(scrub_times)
        print(
            f"scrub (verify):   {t_scrub:.2f}s ({total_gb / t_scrub:.2f} GB/s) "
            f"runs={[round(t, 2) for t in scrub_times]}"
        )

        # Chain-depth sweep: the production resume loop is a LONG chain
        # of increments. Chains collapse to the oldest base, so the
        # numbers to watch at depth are flat-ness: manifest size, take
        # time, and tip-restore latency must NOT grow with depth.
        if args.chain_depth:
            chain_root = os.path.join(root, "chain")
            os.makedirs(chain_root)
            hot_c = hot.copy()
            prev = os.path.join(chain_root, "d0000")
            with override_record_dedup_hashes(True):
                Snapshot.take(
                    prev, {"app": StateDict(frozen=frozen, hot=hot_c)}
                )
            checkpoints = sorted(
                {1, 10, 25, 50, args.chain_depth} | set()
            )
            rows = []
            take_window = []
            for d in range(1, args.chain_depth + 1):
                hot_c = hot_c + np.float32(1)
                path = os.path.join(chain_root, f"d{d:04d}")
                t0 = time.perf_counter()
                Snapshot.take(
                    path,
                    {"app": StateDict(frozen=frozen, hot=hot_c)},
                    incremental_from=prev,
                )
                take_window.append(time.perf_counter() - t0)
                prev = path
                if d in checkpoints:
                    meta = os.path.getsize(
                        os.path.join(path, ".snapshot_metadata")
                    )
                    target = {
                        "app": StateDict(
                            frozen=np.empty_like(frozen),
                            hot=np.empty_like(hot_c),
                        )
                    }
                    t0 = time.perf_counter()
                    Snapshot(path).restore(target)
                    t_restore = time.perf_counter() - t0
                    # Verify BOTH leaves: "hot" is the freshly written
                    # blob, "frozen" is the data that resolved through
                    # the collapsed dedup chain — the path this sweep
                    # exists to exercise.
                    assert np.array_equal(target["app"]["hot"], hot_c)
                    assert np.array_equal(target["app"]["frozen"], frozen)
                    rows.append(
                        (d, meta, min(take_window[-10:]), t_restore)
                    )
            print("chain depth sweep (take = min of last 10):")
            for d, meta, t_take, t_restore in rows:
                print(
                    f"  depth {d:4d}: manifest {meta / 1e3:6.1f} KB, "
                    f"take {t_take:5.2f}s, tip restore {t_restore:5.2f}s"
                )
            # Compare deep vs the depth-10 row: both are min-of-10
            # samples (depth 1 is a single sample that also carries the
            # chain's one-time warmup, so a ratio against it is biased).
            shallow = rows[1] if len(rows) > 1 else rows[0]
            deep = rows[-1]
            print(
                f"  depth {deep[0]} vs {shallow[0]}: "
                f"manifest {deep[1] / shallow[1]:.2f}x, "
                f"take {deep[2] / shallow[2]:.2f}x, "
                f"restore {deep[3] / shallow[3]:.2f}x (flat = 1.0x)"
            )
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
