"""async_take under high-latency storage: blocked time vs total time.

On a fast local disk, staging and I/O finish together, so async_take's
advantage is invisible (benchmarks/embedding measures that case). This
harness injects a fixed per-request latency into the fs plugin — the
cloud-storage shape, ~50 ms RTT per object — WITHOUT disk-bandwidth
noise, and reports the split the reference's torchrec benchmark reports
(benchmarks/torchrec/main.py:133-151):

- sync take: training blocked for the WHOLE wall time;
- async take: blocked only for staging (+ the latency the scheduler
  cannot hide); storage I/O drains behind training.

Run: python benchmarks/async_latency/main.py [--latency-ms 50] [--mb 256]
"""

import argparse
import asyncio
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--latency-ms", type=float, default=50.0)
    parser.add_argument("--mb", type=float, default=256.0)
    parser.add_argument("--runs", type=int, default=3)
    args = parser.parse_args()

    import numpy as np

    from tpusnap import Snapshot, StateDict
    from tpusnap.storage_plugin import (
        register_storage_plugin,
        unregister_storage_plugin,
    )
    from tpusnap.storage_plugins.fs import FSStoragePlugin

    latency = args.latency_ms / 1e3

    class HighLatencyFS(FSStoragePlugin):
        """Local fs with a fixed per-request latency — the cloud-object-
        store shape, minus bandwidth noise."""

        async def write(self, write_io):
            await asyncio.sleep(latency)
            await super().write(write_io)

        async def read(self, read_io):
            await asyncio.sleep(latency)
            await super().read(read_io)

    register_storage_plugin("slowfs", lambda path, opts: HighLatencyFS(path, opts))
    root = tempfile.mkdtemp(prefix="tpusnap_async_lat_")
    try:
        rng = np.random.default_rng(0)
        n_arrays = 16
        per = int(args.mb * 1024**2) // n_arrays
        state = StateDict(
            **{
                f"w{i}": rng.standard_normal(per // 4).astype(np.float32)
                for i in range(n_arrays)
            }
        )
        nbytes = sum(a.nbytes for a in state.values())
        print(
            f"{nbytes / 1e6:.0f} MB over {n_arrays} blobs, "
            f"+{args.latency_ms:.0f} ms per storage request"
        )

        sync_times, blocked_times, total_times = [], [], []
        for run in range(args.runs):
            t0 = time.perf_counter()
            Snapshot.take(f"slowfs://{root}/sync{run}", {"app": state})
            sync_times.append(time.perf_counter() - t0)

            t0 = time.perf_counter()
            pending = Snapshot.async_take(
                f"slowfs://{root}/async{run}", {"app": state}
            )
            blocked_times.append(time.perf_counter() - t0)
            pending.wait()
            total_times.append(time.perf_counter() - t0)

        sync_t = min(sync_times)
        blocked = min(blocked_times)
        total = min(total_times)
        print(
            f"sync take:   {sync_t:.2f}s blocked (100% of the snapshot) "
            f"runs={[round(t, 2) for t in sync_times]}"
        )
        print(
            f"async take:  {blocked:.2f}s blocked / {total:.2f}s total "
            f"(training stalls {100 * blocked / total:.0f}% of the snapshot; "
            f"{sync_t / blocked:.1f}x less than sync) "
            f"blocked_runs={[round(t, 2) for t in blocked_times]}"
        )
    finally:
        unregister_storage_plugin("slowfs")
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
