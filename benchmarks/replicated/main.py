"""Replicated (DDP-analog) snapshot benchmark.

Mirrors /root/reference/benchmarks/ddp/main.py:53-70: N data-parallel
ranks hold identical state; compare

- ``pickle.dump`` from rank 0 only (the ``torch.save`` baseline), vs
- ``Snapshot.take(replicated=["**"])`` — write load spread over all
  ranks by the partitioner.

Run: python benchmarks/replicated/main.py [--world-size 2] [--gb 1.0]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def worker(work_dir: str, gb: str) -> None:
    import numpy as np

    import jax

    from tpusnap import PytreeState, Snapshot
    from tpusnap.comm import get_communicator

    rank = jax.process_index()
    nbytes = int(float(gb) * 1024**3)
    n_arrays = 8
    rng = np.random.default_rng(0)  # same seed → identical state per rank
    state = {
        f"w{i}": rng.integers(0, 2**16, nbytes // n_arrays // 2, dtype=np.uint16)
        for i in range(n_arrays)
    }

    comm = get_communicator()
    # Baseline: single-rank pickle (the torch.save analog).
    if rank == 0:
        import pickle

        t0 = time.perf_counter()
        with open(os.path.join(work_dir, "baseline.pkl"), "wb") as f:
            pickle.dump(state, f, protocol=4)
        baseline_s = time.perf_counter() - t0
        print(f"baseline pickle.dump: {baseline_s:.2f}s "
              f"({nbytes / baseline_s / 1e9:.2f} GB/s)")
    comm.barrier()

    t0 = time.perf_counter()
    Snapshot.take(
        os.path.join(work_dir, "snap"), {"m": PytreeState(state)}, replicated=["**"]
    )
    take_s = time.perf_counter() - t0
    # Per-rank write volume: the partitioner's whole point is spreading
    # the replicated bytes over every rank (reference
    # benchmarks/ddp/README.md:15-24 scales BECAUSE of this); the
    # per-rank split is the direct evidence.
    from tpusnap import scheduler as _sched

    my_bytes = _sched.LAST_EXECUTION_STATS.get("write", {}).get("bytes", 0)
    per_rank = comm.all_gather_object(my_bytes)
    if rank == 0:
        split = ", ".join(f"r{r}={b / 1e6:.0f}MB" for r, b in enumerate(per_rank))
        print(f"Snapshot.take (replicated, world={comm.world_size}): "
              f"{take_s:.2f}s ({nbytes / take_s / 1e9:.2f} GB/s) "
              f"per-rank bytes written: [{split}]")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--world-size", type=int, default=2)
    parser.add_argument("--gb", type=float, default=1.0)
    parser.add_argument(
        "--sweep",
        type=str,
        default=None,
        help="comma-separated world sizes (e.g. 1,2,4) — the reference's "
        "scaling-table shape (benchmarks/ddp/README.md:15-24). On a "
        "1-vCPU host aggregate throughput cannot scale (every rank "
        "shares one core and one disk); the table records per-rank "
        "write-load spread and the multi-process overhead instead.",
    )
    args = parser.parse_args()

    from tpusnap.test_utils import run_subprocess_world

    worlds = (
        [int(w) for w in args.sweep.split(",")]
        if args.sweep
        else [args.world_size]
    )
    for world in worlds:
        with tempfile.TemporaryDirectory(prefix="tpusnap_bench_repl_") as work_dir:
            outputs = run_subprocess_world(
                worker,
                world_size=world,
                args=[work_dir, str(args.gb)],
                timeout=600.0,
            )
        for line in outputs[0].strip().splitlines():
            if "GB/s" in line:
                print(line)


if __name__ == "__main__":
    main()
