"""Replicated (DDP-analog) snapshot benchmark.

Mirrors /root/reference/benchmarks/ddp/main.py:53-70: N data-parallel
ranks hold identical state; compare

- ``pickle.dump`` from rank 0 only (the ``torch.save`` baseline), vs
- ``Snapshot.take(replicated=["**"])`` — write load spread over all
  ranks by the partitioner.

Run: python benchmarks/replicated/main.py [--world-size 2] [--gb 1.0]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def worker(work_dir: str, gb: str) -> None:
    import numpy as np

    import jax

    from tpusnap import PytreeState, Snapshot
    from tpusnap.comm import get_communicator

    rank = jax.process_index()
    nbytes = int(float(gb) * 1024**3)
    n_arrays = 8
    rng = np.random.default_rng(0)  # same seed → identical state per rank
    state = {
        f"w{i}": rng.integers(0, 2**16, nbytes // n_arrays // 2, dtype=np.uint16)
        for i in range(n_arrays)
    }

    comm = get_communicator()
    # Baseline: single-rank pickle (the torch.save analog).
    if rank == 0:
        import pickle

        t0 = time.perf_counter()
        with open(os.path.join(work_dir, "baseline.pkl"), "wb") as f:
            pickle.dump(state, f, protocol=4)
        baseline_s = time.perf_counter() - t0
        print(f"baseline pickle.dump: {baseline_s:.2f}s "
              f"({nbytes / baseline_s / 1e9:.2f} GB/s)")
    comm.barrier()

    t0 = time.perf_counter()
    Snapshot.take(
        os.path.join(work_dir, "snap"), {"m": PytreeState(state)}, replicated=["**"]
    )
    take_s = time.perf_counter() - t0
    if rank == 0:
        print(f"Snapshot.take (replicated, world={comm.world_size}): "
              f"{take_s:.2f}s ({nbytes / take_s / 1e9:.2f} GB/s)")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--world-size", type=int, default=2)
    parser.add_argument("--gb", type=float, default=1.0)
    args = parser.parse_args()

    from tpusnap.test_utils import run_subprocess_world

    with tempfile.TemporaryDirectory(prefix="tpusnap_bench_repl_") as work_dir:
        outputs = run_subprocess_world(
            worker,
            world_size=args.world_size,
            args=[work_dir, str(args.gb)],
            timeout=600.0,
        )
    for line in outputs[0].strip().splitlines():
        if "GB/s" in line:
            print(line)


if __name__ == "__main__":
    main()
