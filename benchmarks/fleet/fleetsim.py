"""Fleet soak: N concurrent snapshot lifecycles, one shared tier,
seeded chaos, graded by the fleet observability layer itself.

No reference counterpart (torchsnapshot has no cross-job story at all).
Spawns a small fleet — trainers in a take loop, one continuous delta
stream, one restore loop — every job a real OS process with its own
``TPUSNAP_JOB_ID``, all publishing into one shared ``TPUSNAP_FLEET_DIR``
and all writing through one shared local+remote write-back tier. Seeded
faults (``TPUSNAP_FAULT_SPEC`` on ``chaos+fs://`` remotes) hit selected
jobs:

- a sustained REMOTE OUTAGE window on one trainer's drain,
- a RANK KILL (SIGKILL mid-write) on another — its fleet record must
  stay non-final and keep growing exposure in the fold,
- a WEDGE (SIGSTOP inside a write; the parent SIGCONTs it back) on a
  third,
- a BANDWIDTH CAP starving a fourth's drain, and
- per-op transient faults on the delta stream.

A shared-base BRANCHING cohort (``--branch``, default 4) rides along:
four jobs forked from one base checkpoint write mostly-identical
content through one shared content-addressed store (``TPUSNAP_CAS_DIR``)
under seeded transient faults. The parent grades the storage bill —
aggregate store blob bytes must stay within 1.25× ONE job's logical
bytes (one base + per-job deltas), the store must ``fsck --store``
clean, and the achieved ``cas_dedup_ratio`` lands in the fleet history
event so the trend gate catches dedup regressions.

The sim then grades itself with its own tooling: ``python -m tpusnap
fleet --check`` over the shared fleet dir must be HEALTHY (generous
thresholds — the seeded faults are survivable by design; only the
SIGKILLed job may miss its commit), a per-job committed verdict is
printed from the children's own reports, a ``kind="fleet"`` history
event (worst RPO, aggregate upload lag, merged storage p99, wall) is
recorded for trend gating, and ``history --check --kind fleet`` runs
against it (exit 3 = first run, no baseline yet — accepted).

Run: python benchmarks/fleet/fleetsim.py [--jobs 8] [--takes 3]
     [--mb 4] [--seed 0] [--timeout 300] [--keep]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

RESULT_TAG = "FLEETSIM_RESULT "


# --------------------------------------------------------------- children


def _mk_state(mb: float, seed: int):
    import numpy as np

    from tpusnap import StateDict

    rng = np.random.default_rng(seed)
    n = max(int(mb * 1e6) // 4, 1024)
    return {
        "app": StateDict(
            weights=rng.standard_normal(n).astype(np.float32),
            step=np.int64(0),
        )
    }


def run_trainer(args) -> dict:
    """A training job: ``--takes`` takes through the shared write-back
    tier (local cache + chaos-wrapped shared remote)."""
    import numpy as np

    from tpusnap import Snapshot

    state = _mk_state(args.mb, args.seed + args.index)
    committed = 0
    for k in range(args.takes):
        state["app"]["weights"] += np.float32(1.0)
        state["app"]["step"] = np.int64(k)
        url = (
            f"tier+local={args.base}/local/{args.job}/t{k}"
            f"+remote=chaos+fs://{args.base}/remote/{args.job}/t{k}"
        )
        Snapshot.take(url, state)
        committed += 1
        time.sleep(args.pause)
    return {"committed": committed, "takes": args.takes}


def run_stream(args) -> dict:
    """A continuous-checkpointing job: one delta stream, a handful of
    explicit micro-commits under per-op transient faults."""
    import numpy as np

    from tpusnap import Snapshot

    state = _mk_state(args.mb, args.seed + args.index)
    root = f"chaos+fs://{args.base}/remote/{args.job}/stream"
    stream = Snapshot.stream(root, state, cadence_s=30.0)
    commits = 0
    try:
        for k in range(args.takes):
            state["app"]["weights"] += np.float32(0.5)
            state["app"]["step"] = np.int64(k)
            stream.commit_now()
            commits += 1
            time.sleep(args.pause)
    finally:
        stream.close(final_commit=False)
    return {"committed": commits, "takes": args.takes}


def run_brancher(args) -> dict:
    """A shared-base branching job: every brancher derives the SAME
    seeded base weights (four jobs forked from one base checkpoint)
    plus a tiny per-job delta tensor, and takes through the shared
    content-addressed store — so the fleet's aggregate store footprint
    must stay ~1× one job's bytes, not N×."""
    import numpy as np

    from tpusnap import Snapshot, StateDict

    rng = np.random.default_rng(args.seed)  # NOT + index: shared content
    n = max(int(args.mb * 1e6) // 4, 1024)
    state = {
        "app": StateDict(
            weights=rng.standard_normal(n).astype(np.float32),
            delta=np.random.default_rng(1000 + args.index)
            .standard_normal(256)
            .astype(np.float32),
            step=np.int64(0),
        )
    }
    committed = 0
    for k in range(args.takes):
        # The base evolves IDENTICALLY across branches (same +1.0 walk
        # from the same seed): each generation's weights still dedup
        # store-wide; only each job's small delta tensor is unique.
        state["app"]["weights"] += np.float32(1.0)
        state["app"]["step"] = np.int64(k)
        url = f"chaos+fs://{args.base}/cas_jobs/{args.job}/t{k}"
        Snapshot.take(url, state)
        committed += 1
        time.sleep(args.pause)
    return {"committed": committed, "takes": args.takes}


def run_restorer(args) -> dict:
    """A restore-loop job: seed take, then repeated restores from it
    (the read side of the shared substrate), then one final take so the
    job's last fleet record is a committed one."""
    import numpy as np

    from tpusnap import Snapshot

    state = _mk_state(args.mb, args.seed + args.index)
    seed_path = f"chaos+fs://{args.base}/remote/{args.job}/seed"
    Snapshot.take(seed_path, state)
    restores = 0
    for _ in range(args.takes):
        Snapshot(seed_path).restore(state)
        restores += 1
        time.sleep(args.pause)
    state["app"]["weights"] += np.float32(1.0)
    Snapshot.take(f"chaos+fs://{args.base}/remote/{args.job}/final", state)
    return {"committed": 1 + restores, "takes": args.takes}


def run_readseed(args) -> dict:
    """Takes the ONE shared snapshot the reader cohort serves from
    (plain fs — the read-attribution grade must not ride chaos)."""
    from tpusnap import Snapshot

    state = _mk_state(args.mb, args.seed + 97)
    Snapshot.take(f"{args.base}/shared/seed", state)
    return {"committed": 1, "takes": 1}


def run_reader(args) -> dict:
    """A serving reader over the shared seed snapshot: full restores,
    each attributed by the access ledger into the SHARED telemetry dir
    — the parent grades the cohort's merged ledgers through
    ``tpusnap heatmap --check`` and ``fleet --check``."""
    from tpusnap import Snapshot

    state = _mk_state(args.mb, args.seed + 97)
    snap = Snapshot(f"{args.base}/shared/seed")
    restores = 0
    for _ in range(max(args.takes, 1)):
        snap.restore(state)
        restores += 1
        time.sleep(args.pause)
    return {"committed": 0, "restores": restores, "takes": args.takes}


def child_main(args) -> int:
    t0 = time.time()
    fn = {"trainer": run_trainer, "stream": run_stream,
          "restore": run_restorer, "branch": run_brancher,
          "readseed": run_readseed, "reader": run_reader}[args.role]
    out = {"job": args.job, "role": args.role, "ok": False}
    try:
        out.update(fn(args))
        out["ok"] = True
    except Exception as e:  # report, don't traceback-spam the parent
        out["error"] = f"{type(e).__name__}: {e}"
    out["wall_s"] = round(time.time() - t0, 2)
    print(RESULT_TAG + json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


# ----------------------------------------------------------------- parent

# (role, fault spec for the child's chaos+fs remote). Survivable by
# design except the SIGKILL — that job's missing commit is EXPECTED.
FAULTS = {
    0: "seed=1,outage=write:0:3",  # remote down 3s, drain must ride it out
    1: None,  # placeholder — killed job, spec built from --kill-after
    2: "seed=3,bandwidth_gbps=0.05",  # starved drain pipe
    3: "seed=4,wedge=write:*",  # SIGSTOP mid-write; parent SIGCONTs
}
STREAM_FAULT = "seed=5,transient_per_op=1"


def spawn_job(args, index: int, role: str, base: str, fleet_dir: str):
    job = f"fleetsim-{role}{index}"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TPUSNAP_JOB_ID=job,
        TPUSNAP_FLEET_DIR=fleet_dir,
        TPUSNAP_TELEMETRY_DIR=os.path.join(base, "telemetry", job),
        TPUSNAP_HEARTBEAT_INTERVAL_S="0.1",
    )
    if role == "trainer" and index in FAULTS:
        spec = FAULTS[index]
        if index == 1:
            spec = f"seed=2,crash_after_op=write:{args.kill_after}"
        if spec:
            env["TPUSNAP_FAULT_SPEC"] = spec
    elif role == "stream":
        env["TPUSNAP_FAULT_SPEC"] = STREAM_FAULT
    elif role in ("reader", "readseed"):
        # The whole cohort shares ONE telemetry dir: every reader's
        # access ledger lands under the same access/<digest>/ so the
        # parent's heatmap merge sees all of them. Job ids stay
        # distinct (TPUSNAP_JOB_ID), so ledger files never collide.
        env["TPUSNAP_TELEMETRY_DIR"] = os.path.join(
            base, "telemetry", "readers"
        )
    elif role == "branch":
        # Branchers share one content-addressed store; their snapshot
        # side rides seeded transient faults (survivable by design).
        # Batching is off so the base weights tensor reaches the store
        # as a dedupable blob instead of a uuid-named slab.
        env["TPUSNAP_CAS_DIR"] = os.path.join(base, "cas_store")
        env["TPUSNAP_DISABLE_BATCHING"] = "1"
        env["TPUSNAP_FAULT_SPEC"] = f"seed={7 + index},transient_per_op=1"
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--child", "--role", role, "--index", str(index),
        "--job", job, "--base", base,
        "--takes", str(args.takes), "--mb", str(args.mb),
        "--seed", str(args.seed), "--pause", str(args.pause),
        "--kill-after", str(args.kill_after),
    ]
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    return {"job": job, "role": role, "index": index, "proc": proc,
            "wedged": role == "trainer" and index == 3}


def cli(cmd, env=None):
    r = subprocess.run(
        [sys.executable, "-m", "tpusnap"] + cmd,
        capture_output=True, text=True, env=env,
    )
    return r.returncode, r.stdout, r.stderr


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--jobs", type=int, default=8,
                        help="fleet size (>= 4; default 8)")
    parser.add_argument("--takes", type=int, default=3)
    parser.add_argument("--mb", type=float, default=4.0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pause", type=float, default=0.2,
                        help="per-step sleep inside each job")
    parser.add_argument("--branch", type=int, default=4,
                        help="shared-base branching jobs through one "
                        "content-addressed store (0 disables; default 4)")
    parser.add_argument("--readers", type=int, default=0,
                        help="serving-reader jobs restoring ONE shared "
                        "snapshot; their merged access ledgers are "
                        "graded through heatmap --check and the fleet "
                        "read-amplification gate (0 disables)")
    parser.add_argument("--kill-after", type=int, default=1, dest="kill_after",
                        help="SIGKILL the doomed trainer after its Nth "
                        "remote payload write (per-take plugin "
                        "instances reset the counter — 1 fires in the "
                        "first drain)")
    parser.add_argument("--timeout", type=float, default=300.0)
    parser.add_argument("--keep", action="store_true",
                        help="keep the working directory")
    parser.add_argument("--json", action="store_true")
    # child-mode plumbing
    parser.add_argument("--child", action="store_true")
    parser.add_argument("--role", default=None)
    parser.add_argument("--index", type=int, default=0)
    parser.add_argument("--job", default=None)
    parser.add_argument("--base", default=None)
    args = parser.parse_args()

    if args.child:
        return child_main(args)

    if args.jobs < 4:
        parser.error("--jobs must be >= 4 (trainers + stream + restore)")
    base = args.base or tempfile.mkdtemp(prefix="tpusnap_fleetsim_")
    fleet_dir = os.path.join(base, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    n_trainers = args.jobs - 2
    t0 = time.time()
    jobs = [
        spawn_job(args, i, "trainer", base, fleet_dir)
        for i in range(n_trainers)
    ]
    jobs.append(spawn_job(args, n_trainers, "stream", base, fleet_dir))
    jobs.append(spawn_job(args, n_trainers + 1, "restore", base, fleet_dir))
    for b in range(args.branch):
        jobs.append(
            spawn_job(args, n_trainers + 2 + b, "branch", base, fleet_dir)
        )
    if args.readers:
        # The shared seed must be committed before any reader starts —
        # run the seeding job to completion first (synchronously).
        seed = spawn_job(args, 0, "readseed", base, fleet_dir)
        try:
            seed["proc"].communicate(timeout=args.timeout)
        except subprocess.TimeoutExpired:
            seed["proc"].kill()
        if seed["proc"].returncode != 0:
            print("readseed: FAILED — skipping the reader cohort")
        else:
            for r in range(args.readers):
                jobs.append(
                    spawn_job(args, r, "reader", base, fleet_dir)
                )
    print(f"fleet: {len(jobs)} job(s) under {base} "
          f"(faults on trainers 0-3 + the stream; trainer 1 is doomed; "
          f"{args.branch} branch job(s) share one CAS store; "
          f"{args.readers} reader(s) on one shared snapshot)")

    # Babysit: SIGCONT the wedged job each poll (a running process
    # ignores SIGCONT, a SIGSTOPped one resumes — bounding the freeze
    # to ~one poll interval), hard-kill anything past the deadline.
    deadline = time.time() + args.timeout
    results = {}
    while any(j["proc"].poll() is None for j in jobs):
        for j in jobs:
            if j["wedged"] and j["proc"].poll() is None:
                try:
                    os.kill(j["proc"].pid, signal.SIGCONT)
                except OSError:
                    pass
        if time.time() > deadline:
            for j in jobs:
                if j["proc"].poll() is None:
                    j["proc"].kill()
            break
        time.sleep(1.0)
    for j in jobs:
        stdout, stderr = j["proc"].communicate()
        rc = j["proc"].returncode
        rep = None
        for line in (stdout or "").splitlines():
            if line.startswith(RESULT_TAG):
                rep = json.loads(line[len(RESULT_TAG):])
        results[j["job"]] = {
            "role": j["role"], "rc": rc,
            "report": rep,
            "killed": rc is not None and rc < 0,
        }

    # Per-job committed verdict from the children's own reports.
    doomed = "fleetsim-trainer1"
    print(f"\n{'job':<22} {'role':<8} {'rc':>4} {'committed':>9}  verdict")
    failures = []
    for name, r in sorted(results.items()):
        rep = r["report"] or {}
        committed = rep.get("committed", 0)
        expected_kill = name == doomed
        ok = (rep.get("ok") and r["rc"] == 0) or (expected_kill and r["killed"])
        if expected_kill and r["killed"]:
            verdict = "KILLED (expected)"
        elif ok:
            verdict = "ok"
        else:
            verdict = "FAIL ({})".format(
                rep.get("error") or "rc={}".format(r["rc"])
            )
        if not ok:
            failures.append(name)
        print(f"{name:<22} {r['role']:<8} {str(r['rc']):>4} "
              f"{committed:>9}  {verdict}")

    # Grade 1: the fleet gate over what every job published. Thresholds
    # are generous — the seeded faults are survivable; the gate exists
    # to catch jobs that silently never published or never committed.
    n_readers = sum(1 for j in jobs if j["role"] == "reader")
    fleet_cmd = ["fleet", "--dir", fleet_dir, "--json", "--check",
                 "--rpo", "3600", "--lag-s", "3600"]
    if n_readers:
        # Each reader restores the shared snapshot --takes times, so the
        # merged amplification is ~readers*takes; +1 of slack keeps the
        # gate about attribution working, not scheduling jitter.
        fleet_cmd += ["--max-read-amplification",
                      str(n_readers * max(args.takes, 1) + 1)]
    rc, out, err = cli(fleet_cmd)
    fleet_doc = json.loads(out) if rc in (0, 2, 3) and out else {}
    rollup = fleet_doc.get("rollup") or {}
    print(f"\nfleet --check: rc={rc} "
          f"({(fleet_doc.get('verdict') or '?').upper()}: "
          f"{fleet_doc.get('reason')})")
    if rc != 0:
        failures.append(f"fleet-check-rc{rc}")
    if rollup.get("n_jobs", 0) < len(jobs):
        failures.append(
            f"fleet-records-{rollup.get('n_jobs', 0)}-of-{len(jobs)}"
        )

    # Grade: the shared-base branching scenario's storage bill. The N
    # branch jobs wrote mostly-identical content through one store, so
    # the store's blob bytes must stay ~1× one job's logical bytes
    # (<= 1.25x: one base + per-job deltas + slack), the store must
    # fsck clean, and the achieved dedup ratio feeds the trend gate.
    cas_dedup_ratio = None
    if args.branch:
        from tpusnap.cas import BLOBS_DIR, read_refs_dir

        cas_store = os.path.join(base, "cas_store")
        blobs_dir = os.path.join(cas_store, BLOBS_DIR)
        store_bytes = sum(
            e.stat().st_size
            for e in (os.scandir(blobs_dir) if os.path.isdir(blobs_dir) else [])
            if e.is_file()
        )
        logical_bytes = 0
        for j in jobs:
            if j["role"] != "branch":
                continue
            for k in range(args.takes):
                snap_dir = os.path.join(base, "cas_jobs", j["job"], f"t{k}")
                refs, _store = read_refs_dir(snap_dir)
                logical_bytes += sum(int(rec[0]) for rec in refs.values())
        cas_dedup_ratio = (
            round(logical_bytes / store_bytes, 2) if store_bytes else None
        )
        budget = 1.25 * (logical_bytes / max(args.branch, 1))
        print(f"\ncas store: {store_bytes} blob byte(s) for "
              f"{logical_bytes} logical byte(s) across {args.branch} "
              f"branch job(s) — dedup ratio {cas_dedup_ratio} "
              f"(budget {budget:.0f} B)")
        if store_bytes and store_bytes > budget:
            failures.append(
                f"cas-store-{store_bytes}B-over-{budget:.0f}B-budget"
            )
        rc_s, _, err_s = cli(["fsck", "--store", cas_store])
        print(f"fsck --store: rc={rc_s}")
        if rc_s != 0:
            failures.append(f"cas-fsck-rc{rc_s}")
            if err_s.strip():
                print(err_s.strip())

    # Grade: the reader cohort's merged access ledgers. Every reader's
    # full restore must be attributed (n_readers distinct jobs in the
    # heatmap), coverage must be ~complete, and the merged amplification
    # rides the same generous budget as the fleet gate.
    heatmap_doc = {}
    if n_readers:
        reader_env = dict(
            os.environ,
            TPUSNAP_TELEMETRY_DIR=os.path.join(base, "telemetry", "readers"),
        )
        shared = os.path.join(base, "shared", "seed")
        amp_budget = n_readers * max(args.takes, 1) + 1
        rc_hm, out_hm, err_hm = cli(
            ["heatmap", shared, "--json", "--check",
             "--max-amplification", str(amp_budget)],
            env=reader_env,
        )
        try:
            heatmap_doc = json.loads(out_hm) if out_hm else {}
        except ValueError:
            heatmap_doc = {}
        print(f"\nheatmap --check: rc={rc_hm} — "
              f"{heatmap_doc.get('n_readers', 0)} reader(s), coverage "
              f"{heatmap_doc.get('coverage')}, amplification "
              f"{heatmap_doc.get('amplification')} (budget {amp_budget}x)")
        if rc_hm != 0:
            failures.append(f"heatmap-check-rc{rc_hm}")
            if err_hm.strip():
                print(err_hm.strip())
        if heatmap_doc.get("n_readers", 0) < n_readers:
            failures.append(
                f"heatmap-readers-{heatmap_doc.get('n_readers', 0)}"
                f"-of-{n_readers}"
            )

    # Grade 2: record the fleet soak as a kind="fleet" history event and
    # run the trend gate over it (exit 3 = first run, no baseline).
    wall = round(time.time() - t0, 2)
    w = (rollup.get("storage") or {}).get("write") or {}
    from tpusnap.history import record_event

    record_event({
        "kind": "fleet",
        "ts": time.time(),
        "jobs": len(jobs),
        "committed_jobs": sum(
            1 for r in results.values() if (r["report"] or {}).get("ok")
        ),
        "worst_rpo_s": rollup.get("worst_rpo_s"),
        "lag_bytes_total": rollup.get("lag_bytes_total"),
        "storage_write_p99_s": w.get("p99_s"),
        # No _s suffix: higher is better in the trend gate — a dedup
        # regression (ratio falling toward 1.0) trips history --check.
        "cas_dedup_ratio": cas_dedup_ratio,
        # Reader cohort: attributed readers and the merged cross-reader
        # amplification over the shared snapshot (None when --readers 0).
        "readers": rollup.get("readers"),
        "read_amplification": (
            heatmap_doc.get("amplification")
            if heatmap_doc
            else rollup.get("read_amplification")
        ),
        "wall_s": wall,
    })
    rc_h, out_h, _ = cli(["history", "--check", "--kind", "fleet",
                          "--metric", "wall_s"])
    print(f"history --check --kind fleet: rc={rc_h} "
          f"({'no baseline yet' if rc_h == 3 else out_h.strip()})")
    if rc_h not in (0, 3):
        failures.append(f"history-check-rc{rc_h}")

    if args.json:
        print(json.dumps({
            "jobs": {k: {kk: vv for kk, vv in v.items() if kk != "proc"}
                     for k, v in results.items()},
            "rollup": rollup,
            "fleet_check_rc": rc,
            "wall_s": wall,
            "failures": failures,
        }))
    if not args.keep and not failures:
        import shutil

        shutil.rmtree(base, ignore_errors=True)
    elif failures:
        print(f"(kept {base} for inspection)")
    print(f"\nfleetsim: {len(jobs)} job(s) in {wall:.1f}s — "
          + ("PASS" if not failures else f"FAIL: {failures}"))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
