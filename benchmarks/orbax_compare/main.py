"""Head-to-head vs orbax.checkpoint: save + restore a sharded train state.

The reference benchmarks itself against the incumbent checkpoint path of
its ecosystem (torch.save in benchmarks/ddp, DeepSpeed's native
checkpoint in /root/reference/benchmarks/deepspeed_opt/main.py:27-128).
The JAX ecosystem's incumbent is orbax.checkpoint, so this harness saves
and restores the SAME mesh-sharded transformer train state through both
frameworks and reports wall-clock for each.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/orbax_compare/main.py [--d-model 1024]
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from tpusnap.test_utils import apply_platform_env

apply_platform_env()

import jax

from tpusnap import PytreeState, Snapshot
from tpusnap.models import Transformer, TransformerConfig, make_mesh
from tpusnap.models.transformer import init_train_state


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument(
        "--runs",
        type=int,
        default=3,
        help="samples per phase per framework, interleaved; the virtio "
        "disk swings >2x minute to minute, so best-of-N interleaved is "
        "the fair comparison",
    )
    args = parser.parse_args()

    mesh = make_mesh()
    cfg = TransformerConfig(
        vocab_size=32768,
        d_model=args.d_model,
        n_heads=16,
        n_layers=args.n_layers,
        d_ff=4 * args.d_model,
    )
    model = Transformer(cfg)
    state = init_train_state(model, mesh, jax.random.PRNGKey(0))
    nbytes = sum(leaf.nbytes for leaf in jax.tree.leaves(state))
    print(f"train state: {nbytes / 1e9:.2f} GB over mesh {dict(mesh.shape)}")

    import orbax.checkpoint as ocp

    ckpt = ocp.PyTreeCheckpointer()
    shardings = jax.tree.map(lambda x: x.sharding, state)
    restore_args = jax.tree.map(
        lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings
    )

    ts_saves, ts_loads, ox_saves, ox_loads = [], [], [], []
    work = tempfile.mkdtemp(prefix="tpusnap_bench_orbax_")
    try:
        for run in range(args.runs):
            # --- tpusnap
            ts_dir = os.path.join(work, f"tpusnap{run}")
            os.sync()
            t0 = time.perf_counter()
            Snapshot.take(ts_dir, {"ts": PytreeState(state)})
            ts_saves.append(time.perf_counter() - t0)
            target = PytreeState(jax.tree.map(lambda x: x, state))
            t0 = time.perf_counter()
            Snapshot(ts_dir).restore({"ts": target})
            ts_loads.append(time.perf_counter() - t0)

            # --- orbax
            ox_dir = os.path.join(work, f"orbax{run}")
            os.sync()
            t0 = time.perf_counter()
            ckpt.save(ox_dir, state)
            ox_saves.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            ckpt.restore(
                ox_dir,
                restore_args=ocp.args.PyTreeRestore(restore_args=restore_args)
                if hasattr(ocp, "args")
                else None,
            )
            ox_loads.append(time.perf_counter() - t0)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    ts_save, ts_load = min(ts_saves), min(ts_loads)
    ox_save, ox_load = min(ox_saves), min(ox_loads)
    print(
        f"tpusnap: save {ts_save:.2f}s ({nbytes / ts_save / 1e9:.2f} GB/s), "
        f"restore {ts_load:.2f}s ({nbytes / ts_load / 1e9:.2f} GB/s) "
        f"save_runs={[round(t, 2) for t in ts_saves]} "
        f"restore_runs={[round(t, 2) for t in ts_loads]}"
    )
    print(
        f"orbax:   save {ox_save:.2f}s ({nbytes / ox_save / 1e9:.2f} GB/s), "
        f"restore {ox_load:.2f}s ({nbytes / ox_load / 1e9:.2f} GB/s) "
        f"save_runs={[round(t, 2) for t in ox_saves]} "
        f"restore_runs={[round(t, 2) for t in ox_loads]}"
    )
    print(
        f"speedup: save {ox_save / ts_save:.2f}x, "
        f"restore {ox_load / ts_load:.2f}x"
    )


if __name__ == "__main__":
    main()
