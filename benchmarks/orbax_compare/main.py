"""Head-to-head vs orbax.checkpoint: save + restore a sharded train state.

The reference benchmarks itself against the incumbent checkpoint path of
its ecosystem (torch.save in benchmarks/ddp, DeepSpeed's native
checkpoint in /root/reference/benchmarks/deepspeed_opt/main.py:27-128).
The JAX ecosystem's incumbent is orbax.checkpoint, so this harness saves
and restores the SAME mesh-sharded transformer train state through both
frameworks and reports wall-clock for each — against BOTH orbax
configurations:

- ``orbax-legacy``: synchronous ``PyTreeCheckpointer`` (the simple API
  many codebases still call);
- ``orbax-prod``: ``AsyncCheckpointer`` + OCDBT + zarr3 — the
  configuration orbax documents for production training loops. For the
  async pair (orbax-prod save vs tpusnap ``async_take``) the table
  reports BLOCKED time (how long training is stopped — the number an
  async checkpointer exists to minimize) and TOTAL time (until the
  snapshot is durable) separately.

Protocol (ROADMAP 5b / VERDICT r5 "weak #4"): every sample cell is one
of ``--runs`` (default 5) INTERLEAVED sessions — tpusnap and both orbax
configs alternate within one disk window per run, so neither framework
monopolizes a fast (or slow) phase of the virtio disk's multi-x swings
— and the HEADLINE statistic is the per-cell **median**, not best-of-N
(best-of-N systematically flatters whichever framework got more
lottery tickets; the median is the honest center). Per-run samples and
best-of-N are still printed for comparability with older rounds, and
the medians are recorded as a ``kind="orbax"`` event in the cross-run
history (fields ``orbax_*``/``ts_*``) so `tpusnap history` can trend
the comparison.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/orbax_compare/main.py [--d-model 1024]
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from tpusnap.test_utils import apply_platform_env

apply_platform_env()

import jax

from tpusnap import PytreeState, Snapshot
from tpusnap.models import Transformer, TransformerConfig, make_mesh
from tpusnap.models.transformer import init_train_state


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--n-layers", type=int, default=8)
    parser.add_argument(
        "--runs",
        type=int,
        default=5,
        help="interleaved sessions per cell (≥5 for the median "
        "protocol; the virtio disk swings >2x minute to minute, so "
        "the frameworks alternate within one window and the median "
        "over sessions is the headline)",
    )
    args = parser.parse_args()

    mesh = make_mesh()
    cfg = TransformerConfig(
        vocab_size=32768,
        d_model=args.d_model,
        n_heads=16,
        n_layers=args.n_layers,
        d_ff=4 * args.d_model,
    )
    model = Transformer(cfg)
    state = init_train_state(model, mesh, jax.random.PRNGKey(0))
    nbytes = sum(leaf.nbytes for leaf in jax.tree.leaves(state))
    print(f"train state: {nbytes / 1e9:.2f} GB over mesh {dict(mesh.shape)}")

    import orbax.checkpoint as ocp

    legacy = ocp.PyTreeCheckpointer()
    # Production orbax: async save, OCDBT aggregation, zarr3.
    prod = ocp.AsyncCheckpointer(
        ocp.PyTreeCheckpointHandler(use_ocdbt=True, use_zarr3=True)
    )
    shardings = jax.tree.map(lambda x: x.sharding, state)
    restore_args = jax.tree.map(
        lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings
    )

    def restore_kwargs():
        return dict(
            restore_args=ocp.args.PyTreeRestore(restore_args=restore_args)
            if hasattr(ocp, "args")
            else None
        )

    # name -> list of samples
    res = {
        k: []
        for k in (
            "ts_save", "ts_load", "ts_async_blocked", "ts_async_total",
            "legacy_save", "legacy_load",
            "prod_blocked", "prod_total", "prod_load",
        )
    }
    work = tempfile.mkdtemp(prefix="tpusnap_bench_orbax_")
    try:
        for run in range(args.runs):
            # --- tpusnap sync
            ts_dir = os.path.join(work, f"tpusnap{run}")
            os.sync()
            t0 = time.perf_counter()
            Snapshot.take(ts_dir, {"ts": PytreeState(state)})
            res["ts_save"].append(time.perf_counter() - t0)
            target = PytreeState(jax.tree.map(lambda x: x, state))
            t0 = time.perf_counter()
            Snapshot(ts_dir).restore({"ts": target})
            res["ts_load"].append(time.perf_counter() - t0)

            # --- tpusnap async (the pair for orbax-prod's async save)
            tsa_dir = os.path.join(work, f"tpusnap_async{run}")
            os.sync()
            t0 = time.perf_counter()
            pending = Snapshot.async_take(tsa_dir, {"ts": PytreeState(state)})
            res["ts_async_blocked"].append(time.perf_counter() - t0)
            pending.wait()
            res["ts_async_total"].append(time.perf_counter() - t0)

            # --- orbax legacy (sync PyTreeCheckpointer)
            ox_dir = os.path.join(work, f"orbax{run}")
            os.sync()
            t0 = time.perf_counter()
            legacy.save(ox_dir, state)
            res["legacy_save"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            legacy.restore(ox_dir, **restore_kwargs())
            res["legacy_load"].append(time.perf_counter() - t0)

            # --- orbax production (AsyncCheckpointer + OCDBT + zarr3)
            oxp_dir = os.path.join(work, f"orbax_prod{run}")
            os.sync()
            t0 = time.perf_counter()
            prod.save(oxp_dir, state)
            res["prod_blocked"].append(time.perf_counter() - t0)
            prod.wait_until_finished()
            res["prod_total"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            prod.restore(oxp_dir, **restore_kwargs())
            res["prod_load"].append(time.perf_counter() - t0)
    finally:
        prod.close()
        shutil.rmtree(work, ignore_errors=True)

    from statistics import median

    med = {k: median(v) for k, v in res.items()}
    best = {k: min(v) for k, v in res.items()}

    def row(name, seconds, best_s, note=""):
        print(
            f"{name:24s} {seconds:7.2f}s  {nbytes / seconds / 1e9:6.2f} GB/s"
            f"  (best {best_s:.2f}s)" + (f"  {note}" if note else "")
        )

    print(
        f"samples per cell: {args.runs} interleaved session(s); "
        "MEDIAN shown (best-of-N in parentheses for round-to-round "
        "comparability)"
    )
    row("tpusnap save", med["ts_save"], best["ts_save"])
    row("tpusnap async blocked", med["ts_async_blocked"],
        best["ts_async_blocked"], "training stalled for this long")
    row("tpusnap async total", med["ts_async_total"], best["ts_async_total"])
    row("tpusnap restore", med["ts_load"], best["ts_load"])
    row("orbax-legacy save", med["legacy_save"], best["legacy_save"],
        "PyTreeCheckpointer")
    row("orbax-legacy restore", med["legacy_load"], best["legacy_load"])
    row("orbax-prod blocked", med["prod_blocked"], best["prod_blocked"],
        "AsyncCheckpointer+OCDBT+zarr3")
    row("orbax-prod total", med["prod_total"], best["prod_total"])
    row("orbax-prod restore", med["prod_load"], best["prod_load"])
    speedups = {
        "legacy_save": med["legacy_save"] / med["ts_save"],
        "legacy_restore": med["legacy_load"] / med["ts_load"],
        "prod_blocked": med["prod_blocked"] / med["ts_async_blocked"],
        "prod_total": med["prod_total"] / med["ts_async_total"],
        "prod_restore": med["prod_load"] / med["ts_load"],
    }
    print(
        "speedups vs orbax-legacy (median/median): "
        f"save {speedups['legacy_save']:.2f}x, "
        f"restore {speedups['legacy_restore']:.2f}x"
    )
    print(
        "speedups vs orbax-prod (median/median):   "
        f"blocked {speedups['prod_blocked']:.2f}x, "
        f"total {speedups['prod_total']:.2f}x, "
        f"restore {speedups['prod_restore']:.2f}x"
    )
    print("runs:", {k: [round(t, 2) for t in v] for k, v in res.items()})

    # Record the medians into the cross-run history under its OWN kind
    # ("orbax", not "bench"): check_regression's comparability filter
    # matches kind/rank/world_size only, so sharing kind="bench" with
    # bench.py's large-workload events would let this smaller workload's
    # throughput grade against theirs and fire spurious regressions.
    # Queryable/gateable via `tpusnap history --kind orbax
    # --metric orbax_speedup_save`.
    try:
        from tpusnap import history as _hist

        _hist.record_event(
            {
                "v": 1,
                "ts": round(time.time(), 3),
                "kind": "orbax",
                "bench": "orbax_compare",
                "rank": 0,
                "world_size": 1,
                "bytes": nbytes,
                "sessions": args.runs,
                "wall_s": round(med["ts_save"], 3),
                "throughput_gbps": round(nbytes / med["ts_save"] / 1e9, 3),
                **{
                    f"{k}_median_s": round(v, 3) for k, v in med.items()
                },
                "orbax_speedup_save": round(speedups["legacy_save"], 3),
                "orbax_speedup_restore": round(
                    speedups["legacy_restore"], 3
                ),
                "orbax_prod_speedup_blocked": round(
                    speedups["prod_blocked"], 3
                ),
                "orbax_prod_speedup_total": round(
                    speedups["prod_total"], 3
                ),
                "orbax_prod_speedup_restore": round(
                    speedups["prod_restore"], 3
                ),
            }
        )
    except Exception as e:
        # The trend is the point of the protocol change — a silently
        # unrecorded run would only be noticed rounds later.
        print(f"WARNING: orbax history event not recorded: {e!r}", file=sys.stderr)


if __name__ == "__main__":
    main()
