"""Memory-budgeted random access: load one big array under a 100MB cap.

Mirrors /root/reference/benchmarks/load_tensor/main.py:24-61 (10GB
tensor, 100MB budget): ``read_object`` splits the read into byte-ranged
tiles so peak host RSS stays near the budget instead of the full array
size, validated with the RSS profiler.

Run: python benchmarks/load_tensor/main.py [--gb 1.0]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

import numpy as np

from tpusnap import PytreeState, Snapshot, measure_rss_deltas

BUDGET = 100 * 1024 * 1024


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--gb", type=float, default=1.0)
    args = parser.parse_args()

    n_rows = int(args.gb * 1024**3) // (4 * 1024)  # 1024 f32 cols per row
    arr = np.random.default_rng(0).standard_normal((n_rows, 1024)).astype(np.float32)

    with tempfile.TemporaryDirectory(prefix="tpusnap_bench_load_") as work_dir:
        path = os.path.join(work_dir, "snap")
        Snapshot.take(path, {"m": PytreeState({"big": arr})})
        snapshot = Snapshot(path)

        # Budgeted pass first: it must see a clean RSS baseline — a prior
        # unbudgeted pass leaves the allocator's retained pages inflated
        # and would make the budget check vacuous.
        for label, budget in ((f"{BUDGET >> 20}MB budget", BUDGET), ("unbudgeted", None)):
            deltas = []
            t0 = time.perf_counter()
            with measure_rss_deltas(deltas):
                out = snapshot.read_object(
                    "0/m/big", memory_budget_bytes=budget
                )
            load_s = time.perf_counter() - t0
            assert out.shape == arr.shape
            del out
            print(
                f"read_object {label}: {load_s:.2f}s "
                f"({arr.nbytes / load_s / 1e9:.2f} GB/s), "
                f"peak RSS delta {max(deltas) / 1e6:.0f} MB"
            )


if __name__ == "__main__":
    main()
