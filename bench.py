"""Headline benchmark: Snapshot.take throughput to local FS, decomposed.

Mirrors the reference's published benchmark (single-accelerator DDP take
to local FS, /root/reference/benchmarks/ddp/README.md:17 — 20 GB in
~13.91 s ≈ 1.438 GB/s on one A100; DtoH over PCIe is not the bottleneck
there, storage I/O is). ``vs_baseline`` is the throughput ratio against
that 1.438 GB/s.

Besides the headline number the JSON carries a decomposition so the
result is interpretable on any disk:
- ``roofline_gbps``: in-harness write roofline — the same 16-file layout
  written as raw streams through the SAME native write engine (same
  buffer-alignment class as user state arrays, so the same
  RWF_DONTCACHE/O_DIRECT routing), same thread pool, zero snapshot
  machinery on top. It is the fastest this byte layout can move with the
  take's own engine and durability semantics, so ``roofline_fraction``
  (take / roofline) reads directly as pipeline efficiency; values near
  (or, under disk-bandwidth swings between the interleaved samples,
  slightly above) 1.0 mean the pipeline adds nothing.
- The A100 baseline machine's local NVMe sustains multi-GB/s; this VM's
  virtio disk measures ~1-2 GB/s and swings >2x minute to minute
  (single-stream plain-buffered writes are host-throttled to ~0.2 GB/s),
  so the fraction — not the absolute number — is the portable verdict
  on the pipeline.
- ``staging_s`` / ``residual_io_s``: the scheduler's split of the best
  take (staging = the window training would be blocked in async_take).
- ``restore_gbps``: cold-cache restore throughput of the same snapshot,
  with a cold-read roofline sampled INTERLEAVED (same native read
  engine + 8-stream pool reading the snapshot's own blobs):
  - ``restore_roofline_gbps``: engine reads into FRESH unaligned numpy
    buffers — what any checkpoint reader delivering bytes into
    user-owned memory must do, including the ~2 GB of page faults. The
    like-for-like ceiling; ``restore_roofline_fraction`` is restore
    against this.
  - ``restore_roofline_prefaulted_gbps``: same reads into pre-faulted
    reused buffers — the disk-only ceiling with zero memory-management
    cost. The spread between the two rooflines is page-fault cost, not
    pipeline waste.
  - ``restore_roofline_verified_gbps``: prefaulted reads WITH the fused
    integrity CRC — the work a verifying restore cannot skip, so
    ``restore_roofline_verified_fraction`` is the honest pipeline
    efficiency; the prefaulted-minus-verified spread is pure checksum
    cost (one fused pass, ~5 GB/s on this host's single core).
  - ``restore_warm_gbps``: restore into already-faulted targets — the
    PRODUCTION case (a resume loop restores into existing training
    state). ``restore_gbps`` uses brand-new cold buffers, the worst
    case: at high memory commit the kernel's fresh-anon-page zeroing
    collapses (raw engine 0.18 GB/s at 20 GB here), an artifact of the
    fresh-buffer benchmark shape, not of the restore pipeline.
  Restore reads land IN PLACE in the target arrays (native fused
  read+checksum, no scratch buffer, no separate verify/copy passes), so
  the verified restore tracks the fresh-destination roofline closely.

- ``incremental_take_s`` / ``incremental_effective_gbps``: an
  ``incremental_from=`` take of the UNCHANGED state against the last
  snapshot — all blobs dedup, so the cost is one CRC pass and no
  storage I/O (~9-10 GB/s effective on this host).
- ``scrub_gbps`` / ``scrub_clean``: ``verify_snapshot`` re-reading and
  checksum-verifying every stored byte. Like take and restore, the
  scrub is sampled INTERLEAVED with its own roofline
  (``scrub_roofline_gbps``): the exact byte ranges the scrub verifies,
  read through the same native fused read+CRC engine at the same
  concurrency (TPUSNAP_SCRUB_CONCURRENCY slots, reused scratch), with
  zero manifest/asyncio machinery on top. ``scrub_roofline_fraction``
  (best scrub / best roofline) is therefore pure pipeline efficiency;
  with per-run samples listed, a slow-disk window (this host swings
  >2x) shows up as BOTH numbers dropping while the fraction holds.

Run policy: every timed section is preceded by ``os.sync()`` so it
competes only with its own I/O, not earlier sections' writeback. The
restore loop runs one UNTIMED warmup restore first (reported as
``restore_warmup_s``): it absorbs one-time costs — module imports,
native-library load, allocator growth, and the host-side writeback of
the snapshot just taken — that belong to process startup, not the
restore path (r03 measured an 11.9 s first run vs 2.0 s steady-state;
the warmup makes that split explicit instead of folding it into min()).

Memory accounting: ``async_take_peak_rss_mb`` is the peak RSS delta
(rss_profiler, 100 ms sampling) over one async take at bench scale —
the defensive-clone path, where RSS MUST move, so the field doubles as
the sampler's self-check (the former sync-take take_peak_rss_mb was
pinned at ~0 by zero-copy staging and carried no information) —
alongside ``async_take_blocked_s`` (the staging-priority blocked
window) and ``memory_budget_gb``, the scheduler budget the takes ran
under — together the evidence for the reference's signature "adapts to
host RAM" property (reference benchmarks/load_tensor/main.py:39-44).
Set TPUSNAP_BENCH_BYTES to shrink the run below the default
baseline-scale 20 GB.

The state is **host-resident** (numpy): this benchmark measures the
framework pipeline — zero-copy serialization, budget-gated scheduling,
batched storage I/O — which is the part the framework controls. In this
environment the TPU chip is reached through a proxied PJRT tunnel whose
device→host link moves ~10 MB/s (measured; real v5e HBM→host DMA is
tens of GB/s), so including a device transfer would only measure the
tunnel. Device-array staging (async DtoH enqueued at prepare time,
overlapped with I/O) is exercised by tests/test_snapshot.py instead.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import shutil
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

# Reference: 20 GB / 13.91 s on 1×A100, local FS (BASELINE.md).
BASELINE_GBPS = 20.0 / 13.91

# Default = the baseline's own scale (20 GB, reference
# benchmarks/ddp/README.md:17) so vs_baseline compares like with like;
# TPUSNAP_BENCH_BYTES shrinks it for quick local runs.
TOTAL_BYTES = int(os.environ.get("TPUSNAP_BENCH_BYTES", 20 * 1024**3))
N_ARRAYS = 16
N_TAKE_RUNS = int(os.environ.get("TPUSNAP_BENCH_RUNS", 4))


def _drop_caches() -> bool:
    try:
        os.sync()
        with open("/proc/sys/vm/drop_caches", "w") as f:
            f.write("3")
        return True
    except OSError:
        return False


def measure_roofline(tmp: str, nbytes_per_file: int, n_files: int) -> float:
    """Raw aggregate write throughput for the snapshot's exact file
    layout: same native write engine, same 8-worker pool the fs plugin
    uses, same buffer alignment class as user state arrays (numpy
    allocations are not page-aligned), no snapshot machinery on top. This
    is the fastest any checkpoint writer could move these bytes with
    these durability semantics."""
    from tpusnap import _native as native

    # +16 offset: match the alignment class of numpy-owned state arrays
    # so the roofline exercises the same engine the take's writes do.
    buf = native.aligned_empty(nbytes_per_file + 16)[16:]
    # Random payload: constant fill could be flattered by host-side
    # image compression and would not match what the take writes.
    buf[:] = np.random.default_rng(1).integers(
        0, 255, nbytes_per_file, dtype=np.uint8
    )
    best = 0.0
    for _ in range(2):
        os.sync()
        ex = ThreadPoolExecutor(max_workers=8)
        t0 = time.perf_counter()
        list(
            ex.map(
                lambda i: native.write_file(os.path.join(tmp, f"r{i}"), buf),
                range(n_files),
            )
        )
        el = time.perf_counter() - t0
        ex.shutdown()
        for i in range(n_files):
            os.unlink(os.path.join(tmp, f"r{i}"))
        best = max(best, nbytes_per_file * n_files / el / 1e9)
    return best


def main() -> None:
    from tpusnap import PytreeState, Snapshot
    from tpusnap import scheduler as _sched

    per_array = TOTAL_BYTES // N_ARRAYS
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 2**16, per_array // 2, dtype=np.uint16)
    state = {
        # distinct buffers (shifted views copied) so no write dedups
        f"w{i}": np.roll(raw, i).view(np.float16)
        for i in range(N_ARRAYS)
    }
    nbytes = sum(a.nbytes for a in state.values())

    bench_root = tempfile.mkdtemp(prefix="tpusnap_bench_")
    try:
        # Restore first, from a single settled snapshot: the bench writes
        # ~20 GB overall, and the host keeps flushing guest writes for
        # many seconds after the guest's own sync returns — cold reads
        # measured in that window only show the host's writeback, not the
        # restore path.
        restore_snap = os.path.join(bench_root, "restore_src", "snap")
        Snapshot.take(restore_snap, {"model": PytreeState(state)})
        os.sync()
        time.sleep(8.0)

        import glob as _glob

        from tpusnap import _native as _nat

        blob_files = [
            f
            for f in _glob.glob(os.path.join(restore_snap, "**", "*"), recursive=True)
            if os.path.isfile(f) and not f.endswith(".snapshot_metadata")
        ]
        blob_sizes = {f: os.path.getsize(f) for f in blob_files}
        prefaulted = {
            f: np.empty(blob_sizes[f], dtype=np.uint8) for f in blob_files
        }
        for buf_ in prefaulted.values():
            buf_[::4096] = 0  # fault every page once

        def _engine_read_all(dests, want_crc: bool = False) -> float:
            """Cold aggregate read of the snapshot's blobs through the
            same native engine + 8-stream pool the restore uses.
            ``want_crc=True`` fuses the integrity CRC into the reads —
            the work a VERIFYING restore cannot skip, so the
            prefaulted+CRC variant is the like-for-like ceiling for
            ``restore_gbps`` (the plain variants isolate page-fault and
            checksum cost instead)."""
            _drop_caches()

            def read_one(f):
                n = blob_sizes[f]
                out = dests[f] if dests is not None else np.empty(n, np.uint8)
                got, _, _ = _nat.read_range_into(f, 0, n, out, want_crc=want_crc)
                assert got == n

            ex = ThreadPoolExecutor(max_workers=8)
            t0 = time.perf_counter()
            list(ex.map(read_one, blob_files))
            el = time.perf_counter() - t0
            ex.shutdown()
            return sum(blob_sizes.values()) / el / 1e9

        # Untimed warmup restore: absorbs one-time costs (imports, native
        # lib load, allocator growth, residual host writeback of the
        # snapshot written above) so the timed runs measure the restore
        # path, not process startup. Reported, never counted.
        t0 = time.perf_counter()
        Snapshot(restore_snap).restore(
            {
                "model": PytreeState(
                    {f"w{i}": np.empty_like(state[f"w{i}"]) for i in range(N_ARRAYS)}
                )
            }
        )
        restore_warmup_s = time.perf_counter() - t0

        # The disk's bandwidth swings >2x minute to minute, so roofline
        # and restore are sampled interleaved (same reasoning as the
        # write side below).
        restore_runs = []
        restore_warm_runs = []
        restore_rooflines = []
        restore_rooflines_prefaulted = []
        restore_rooflines_verified = []
        # Warm-target restore destinations — the PRODUCTION case: a
        # resume loop restores into long-lived existing training state
        # whose pages are already faulted. Allocated ONCE and reused
        # across runs, like real training state. (The fresh
        # np.empty_like targets below are the worst case; at high
        # memory commit the kernel's fresh-anon-page zeroing collapses
        # — measured 0.18 GB/s raw-engine at 20 GB — an artifact of
        # benchmarking into brand-new buffers, not of the pipeline.)
        warm_target = {
            f"w{i}": np.zeros_like(state[f"w{i}"]) for i in range(N_ARRAYS)
        }
        for _ in range(3):
            restore_rooflines.append(_engine_read_all(None))
            restore_rooflines_prefaulted.append(_engine_read_all(prefaulted))
            restore_rooflines_verified.append(
                _engine_read_all(prefaulted, want_crc=True)
            )
            _drop_caches()
            t0 = time.perf_counter()
            Snapshot(restore_snap).restore(
                {"model": PytreeState(warm_target)}
            )
            restore_warm_runs.append(time.perf_counter() - t0)
            cold = _drop_caches()
            target = {
                f"w{i}": np.empty_like(state[f"w{i}"]) for i in range(N_ARRAYS)
            }
            app_state = {"model": PytreeState(target)}
            t0 = time.perf_counter()
            Snapshot(restore_snap).restore(app_state)
            restore_runs.append(time.perf_counter() - t0)
        del prefaulted
        restore_el = min(restore_runs)
        restore_gbps = nbytes / restore_el / 1e9
        restore_roofline = max(restore_rooflines)
        # Bit-pattern comparison: random f16 buffers contain NaNs, and
        # NaN != NaN would fail a value comparison on correct data.
        ok = all(
            np.array_equal(
                app_state["model"].tree[f"w{i}"].view(np.uint16),
                state[f"w{i}"].view(np.uint16),
            )
            for i in (0, N_ARRAYS - 1)
        ) and all(
            # The warm-target (production-case) headline must be just as
            # verified as the cold one.
            np.array_equal(
                warm_target[f"w{i}"].view(np.uint16),
                state[f"w{i}"].view(np.uint16),
            )
            for i in (0, N_ARRAYS - 1)
        )
        del target, app_state, warm_target
        shutil.rmtree(os.path.join(bench_root, "restore_src"), ignore_errors=True)

        # The virtio disk's bandwidth swings >2x on multi-second timescales
        # (host contention), so roofline and take are sampled INTERLEAVED —
        # comparing a lucky roofline window against an unlucky take window
        # would say "pipeline overhead" where there is only disk noise.
        from tpusnap.rss_profiler import measure_rss_deltas

        times = []
        splits = []
        rooflines = []
        budget_bytes = None
        for run in range(N_TAKE_RUNS):
            rooflines.append(
                measure_roofline(bench_root, per_array, N_ARRAYS)
            )
            tmp = os.path.join(bench_root, f"take{run}")
            app_state = {"model": PytreeState(state)}
            # Drain pending page-cache writeback from earlier iterations so
            # each timed take competes only with its own I/O.
            os.sync()
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(tmp, "snap"), app_state)
            times.append(time.perf_counter() - t0)
            stats = _sched.LAST_EXECUTION_STATS.get("write", {})
            budget_bytes = stats.get("budget_bytes") or budget_bytes
            splits.append(
                (stats.get("staging_s"), stats.get("total_s"))
            )
            if run + 1 < N_TAKE_RUNS:
                shutil.rmtree(tmp, ignore_errors=True)
        best_i = min(range(len(times)), key=times.__getitem__)
        best = times[best_i]
        gbps = nbytes / best / 1e9
        staging_s, sched_total_s = splits[best_i]
        roofline = max(rooflines)

        # Async-take leg at bench scale: the blocked window (under
        # staging-priority scheduling this is the defensive-clone pass)
        # and its peak RSS. This replaces the former sync-take
        # take_peak_rss_mb, which was pinned at ~0 by design (sync
        # takes of numpy state stage zero-copy views) and therefore
        # indistinguishable from a broken sampler — the async clone
        # path is the configuration where RSS MUST move, so the field
        # doubles as the sampler's self-check.
        async_dir = os.path.join(bench_root, "async_take", "snap")
        os.sync()
        rss_deltas = []
        t0 = time.perf_counter()
        with measure_rss_deltas(rss_deltas):
            pending = Snapshot.async_take(
                async_dir, {"model": PytreeState(state)}
            )
            async_blocked_s = time.perf_counter() - t0
            pending.wait()
        async_total_s = time.perf_counter() - t0
        async_peak_rss = max(rss_deltas, default=0)
        shutil.rmtree(os.path.dirname(async_dir), ignore_errors=True)

        # Beyond-reference capabilities, measured on the last snapshot:
        # an incremental take of the UNCHANGED state (all blobs dedup —
        # cost is one CRC pass, no storage I/O) and a full integrity
        # scrub (every stored byte re-read and verified).
        from tpusnap import verify_snapshot

        last_snap = os.path.join(
            bench_root, f"take{N_TAKE_RUNS - 1}", "snap"
        )
        # The incremental base records 64-bit dedup hashes
        # (TPUSNAP_RECORD_DEDUP_HASHES — the documented pattern for
        # bases of planned chains): skip decisions need 64-bit evidence
        # on both sides, and a plain base conservatively rewrites once.
        # Taken untimed so the headline take samples stay hash-lane-free.
        from tpusnap.knobs import override_record_dedup_hashes

        inc_base = os.path.join(bench_root, "inc_base", "snap")
        with override_record_dedup_hashes(True):
            Snapshot.take(inc_base, {"model": PytreeState(state)})
        os.sync()
        inc_path = os.path.join(bench_root, "inc", "snap")
        t0 = time.perf_counter()
        Snapshot.take(
            inc_path, {"model": PytreeState(state)}, incremental_from=inc_base
        )
        inc_take_s = time.perf_counter() - t0
        shutil.rmtree(os.path.join(bench_root, "inc_base"), ignore_errors=True)
        shutil.rmtree(os.path.join(bench_root, "inc"), ignore_errors=True)

        # Scrub, interleaved with its own roofline: the exact byte ranges
        # the scrub verifies, read through the same native fused read+CRC
        # engine at the same concurrency, zero manifest/asyncio machinery.
        # r03 published a single scrub sample with no roofline and the
        # driver caught it 9x low (0.347 vs 3.0 GB/s) — competing with the
        # writeback of the take that preceded it; the sync + interleaved
        # sampling below makes the number self-verifying.
        from tpusnap.inspect import iter_blobs, load_snapshot_metadata
        from tpusnap.knobs import get_scrub_concurrency

        os.sync()
        scrub_manifest = load_snapshot_metadata(last_snap).manifest
        scrub_ranges = []  # (abs_path, offset, nbytes)
        for b in iter_blobs(scrub_manifest):
            off, end = b.byte_range if b.byte_range else (0, None)
            if end is None:
                end = os.path.getsize(os.path.join(last_snap, b.location))
            scrub_ranges.append(
                (os.path.join(last_snap, b.location), off, end - off)
            )
        scrub_bytes = sum(n for _, _, n in scrub_ranges)

        def scrub_roofline_once() -> float:
            _drop_caches()
            n_slots = get_scrub_concurrency()
            scratch = max(n for _, _, n in scrub_ranges)
            local = __import__("threading").local()

            def read_one(rng):
                path_, off_, n_ = rng
                buf = getattr(local, "buf", None)
                if buf is None or buf.nbytes < n_:
                    buf = _nat.aligned_empty(max(n_, scratch))
                    local.buf = buf
                got, _, _ = _nat.read_range_into(
                    path_, off_, n_, memoryview(buf)[:n_], want_crc=True
                )
                assert got == n_

            ex = ThreadPoolExecutor(max_workers=n_slots)
            t0 = time.perf_counter()
            list(ex.map(read_one, scrub_ranges))
            el = time.perf_counter() - t0
            ex.shutdown()
            return scrub_bytes / el / 1e9

        scrub_runs = []
        scrub_rooflines = []
        scrub_clean = True
        for _ in range(2):
            scrub_rooflines.append(scrub_roofline_once())
            _drop_caches()
            t0 = time.perf_counter()
            scrub_report = verify_snapshot(last_snap)
            scrub_runs.append(time.perf_counter() - t0)
            scrub_clean = scrub_clean and scrub_report.clean
        scrub_s = min(scrub_runs)
        scrub_roofline = max(scrub_rooflines)

        # pinned_host (UVM analog) capability probe on the REAL backend,
        # via the wedge-proof runner (own process group, no inherited
        # pipes, group SIGKILL on timeout, one retry) — round 4's
        # subprocess.run(capture_output=...) version blocked draining
        # pipes a surviving tunnel helper held open and lost the leg.
        from tpusnap._subproc import run_hard_timeout

        probe_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks",
            "pinned_host",
            "probe.py",
        )
        health_code = (
            "import json, time, jax, numpy as np, jax.numpy as jnp\n"
            "t0 = time.perf_counter()\n"
            "d = jax.devices()[0]\n"
            "np.asarray(jax.device_put(jnp.ones(1 << 16, jnp.float32), d))\n"
            "print(json.dumps({'platform': d.platform,"
            " 's': round(time.perf_counter() - t0, 2)}))\n"
        )
        try:
            # Fast health gate first: a dead tunnel must cost the bench
            # ~90s with the cause recorded, not 2x the full probe
            # timeout. 45s per attempt covers cold PJRT init (measured
            # 12.6s through the tunnel incl. jax startup); the retry
            # keeps a healthy-but-cold backend from being falsely
            # declared dead by one slow first attempt.
            health = run_hard_timeout(
                [sys.executable, "-c", health_code], timeout_s=45, retries=1
            )
            if health.timed_out or health.returncode != 0:
                pinned_host = {
                    "ok": False,
                    "skipped": True,
                    "error": (
                        "tunnel unhealthy: 45s device-roundtrip probe "
                        + (
                            f"timed out ({health.attempts} attempts)"
                            if health.timed_out
                            else f"rc={health.returncode}: {health.stderr[-200:]}"
                        )
                    ),
                }
            else:
                r = run_hard_timeout(
                    [sys.executable, probe_path], timeout_s=150, retries=1
                )
                if r.timed_out:
                    pinned_host = {
                        "ok": False,
                        "error": "timeout (TPU tunnel hang)",
                        "attempts": r.attempts,
                    }
                else:
                    lines = [
                        ln for ln in r.stdout.strip().splitlines() if ln.strip()
                    ]
                    pinned_host = (
                        json.loads(lines[-1])
                        if lines
                        else {
                            "ok": False,
                            "error": f"rc={r.returncode}: {r.stderr[-200:]}",
                        }
                    )
        except Exception as e:  # noqa: BLE001
            pinned_host = {"ok": False, "error": str(e)}
    finally:
        shutil.rmtree(bench_root, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": "snapshot_take_local_fs",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / BASELINE_GBPS, 3),
                "roofline_gbps": round(roofline, 3),
                "roofline_fraction": round(gbps / roofline, 3),
                "roofline_runs_gbps": [round(r, 3) for r in rooflines],
                "take_runs_s": [round(t, 2) for t in times],
                "staging_s": round(staging_s, 2) if staging_s else None,
                "residual_io_s": (
                    round(sched_total_s - staging_s, 2)
                    if staging_s and sched_total_s
                    else None
                ),
                "restore_gbps": round(restore_gbps, 3),
                "restore_roofline_gbps": round(restore_roofline, 3),
                "restore_roofline_fraction": round(
                    restore_gbps / restore_roofline, 3
                ),
                "restore_roofline_runs_gbps": [
                    round(r, 3) for r in restore_rooflines
                ],
                "restore_roofline_prefaulted_gbps": round(
                    max(restore_rooflines_prefaulted), 3
                ),
                # Prefaulted + fused CRC: the ceiling a VERIFYING restore
                # can actually reach; the fraction against it is the
                # restore pipeline's efficiency net of page-fault and
                # checksum cost (both isolated by the other rooflines).
                "restore_roofline_verified_gbps": round(
                    max(restore_rooflines_verified), 3
                ),
                "restore_roofline_verified_fraction": round(
                    restore_gbps / max(restore_rooflines_verified), 3
                ),
                "restore_runs_s": [round(t, 2) for t in restore_runs],
                "restore_warm_gbps": round(
                    nbytes / min(restore_warm_runs) / 1e9, 3
                ),
                "restore_warm_runs_s": [
                    round(t, 2) for t in restore_warm_runs
                ],
                "restore_warmup_s": round(restore_warmup_s, 2),
                "restore_cold_cache": cold,
                "restore_verified": ok,
                "async_take_blocked_s": round(async_blocked_s, 2),
                "async_take_total_s": round(async_total_s, 2),
                # Clone-path RSS: must be >> 0 (the defensive clones are
                # real allocations) — doubles as the RSS sampler's
                # self-check, unlike the sync take whose zero-copy
                # staging pinned the old take_peak_rss_mb at 0.
                "async_take_peak_rss_mb": round(async_peak_rss / 1e6),
                "memory_budget_gb": (
                    round(budget_bytes / 1e9, 2) if budget_bytes else None
                ),
                "incremental_take_s": round(inc_take_s, 2),
                "incremental_effective_gbps": round(
                    nbytes / inc_take_s / 1e9, 3
                ),
                "scrub_s": round(scrub_s, 2),
                "scrub_gbps": round(scrub_bytes / scrub_s / 1e9, 3),
                "scrub_roofline_gbps": round(scrub_roofline, 3),
                "scrub_roofline_fraction": round(
                    (scrub_bytes / scrub_s / 1e9) / scrub_roofline, 3
                ),
                "scrub_runs_gbps": [
                    round(scrub_bytes / t / 1e9, 3) for t in scrub_runs
                ],
                "scrub_roofline_runs_gbps": [
                    round(r, 3) for r in scrub_rooflines
                ],
                "scrub_clean": scrub_clean,
                "pinned_host": pinned_host,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
