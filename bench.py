"""Headline benchmark: Snapshot.take throughput to local FS, decomposed.

Mirrors the reference's published benchmark (single-accelerator DDP take
to local FS, /root/reference/benchmarks/ddp/README.md:17 — 20 GB in
~13.91 s ≈ 1.438 GB/s on one A100; DtoH over PCIe is not the bottleneck
there, storage I/O is). ``vs_baseline`` is the throughput ratio against
that 1.438 GB/s.

Besides the headline number the JSON carries a decomposition so the
result is interpretable on any disk:
- ``roofline_gbps``: since round 7, the best in-take probe ceiling
  across the full-scale runs (None if every run's probe failed). The
  16-file in-harness roofline (``measure_roofline``: raw streams
  through the SAME native write engine, same buffer-alignment class,
  same thread pool, zero snapshot machinery) still anchors the tight
  ~2 GB fraction probe below. ``roofline_fraction``
  (take / roofline, median of same-window pairs from the tight ~2 GB
  probe — full-scale pairs span minutes and host contention drifts
  inside them; their fractions are published as a diagnostic list)
  reads directly as pipeline efficiency; ~1.0 means the pipeline adds
  nothing.
- ``roofline_fraction_fullscale`` (since round 7): from IN-TAKE
  INTERLEAVED PROBES — TPUSNAP_PROBE pauses the take's own write
  scheduler once per interval and measures the raw ceiling through the
  same plugin stack, so the full-scale fraction's two sides share
  every disk window (the former separate roofline session spanned
  minutes of drift and scattered 0.206–0.707). Probe time is
  subtracted from the reported take times
  (``probe_overhead_s_runs``); ``roofline_runs_gbps`` now carries the
  per-run probe ceilings.
- The A100 baseline machine's local NVMe sustains multi-GB/s; this VM's
  virtio disk measures ~1-2 GB/s and swings >2x minute to minute
  (single-stream plain-buffered writes are host-throttled to ~0.2 GB/s),
  so the fraction — not the absolute number — is the portable verdict
  on the pipeline.
- ``staging_s`` / ``residual_io_s``: the scheduler's split of the best
  take (staging = the window training would be blocked in async_take).
- ``restore_cold_gbps`` / ``restore_warm_gbps``: full-scale ABSOLUTES —
  fresh-target cold restores and warm-target (production resume-loop)
  restores. No fractions are formed at full scale: a 20 GB sample
  spans minutes and the virtio disk drifts several-fold within that,
  so no two full-scale measurements share a window. The restore
  HEADLINES are ``restore_verified_fraction`` + ``restore_warm_gbps``;
  the cold absolute is a demoted diagnostic (``restore_gbps`` remains
  as a deprecated alias for BENCH_r* trend comparability).
- ``restore_verified_fraction`` — the pipeline-efficiency number,
  from a tight-window ~2 GB probe where each paired sample takes
  seconds: median over rounds of (warm-target restore) /
  (prefaulted+CRC engine reads), both sides measured back-to-back in
  one disk window, neither faulting pages, both checksumming every
  byte. The remaining gap is genuinely the pipeline's. (A
  fresh-target/fresh-buffer "cold" pair was tried and dropped —
  fresh-anon page faulting interacts with drop_caches so erratically
  that adjacent samples disagree 100x.)
  Restore reads land IN PLACE in the target arrays (native fused
  read+checksum, no scratch buffer, no separate verify/copy passes).

- ``incremental_take_s`` / ``incremental_effective_gbps``: an
  ``incremental_from=`` take of the UNCHANGED state against the last
  snapshot — all blobs dedup, so the cost is one fused CRC32C+XXH64
  pass and no storage I/O.
- ``delta_rpo_seconds`` / ``delta_write_amplification`` /
  ``delta_commit_overhead_s``: a short ``Snapshot.stream`` soak over a
  training loop mutating ~1/64 of one array per step — the realized
  steady-state RPO (max interval between micro-commits vs the
  configured cadence), delta bytes written over bytes actually
  mutated, and the per-micro-commit capture cost.
- ``scrub_gbps`` / ``scrub_clean``: ``verify_snapshot`` re-reading and
  checksum-verifying every stored byte — full-scale ABSOLUTES, with
  an engine comparator (``scrub_roofline_gbps``: the exact byte
  ranges the scrub verifies, read through the same native fused
  read+CRC engine at the same concurrency) interleaved for context.
  ``scrub_roofline_fraction`` is the median of same-round pairs from
  the tight ~2 GB probe, like the take and restore fractions.

Run policy: every timed section is preceded by ``os.sync()`` so it
competes only with its own I/O, not earlier sections' writeback. The
restore loop runs one UNTIMED warmup restore first (reported as
``restore_warmup_s``): it absorbs one-time costs — module imports,
native-library load, allocator growth, and the host-side writeback of
the snapshot just taken — that belong to process startup, not the
restore path (r03 measured an 11.9 s first run vs 2.0 s steady-state;
the warmup makes that split explicit instead of folding it into min()).

Memory accounting: ``async_take_peak_rss_mb`` is the peak RSS delta
(rss_profiler, 100 ms sampling) over one async take at bench scale —
the defensive-clone path, where RSS MUST move, so the field doubles as
the sampler's self-check (the former sync-take take_peak_rss_mb was
pinned at ~0 by zero-copy staging and carried no information). Under
PIPELINED staging the delta is bounded by the staging window
(``async_stage_window_gb``), not 1x state; ``async_take_blocked_s`` is
the first-window blocked window, with ``async_blocked_vs_sync_take``
and ``async_breakeven_overlap_s`` the sync/async crossover pair —
together with ``memory_budget_gb`` the evidence for the reference's
signature "adapts to host RAM" property (reference
benchmarks/load_tensor/main.py:39-44).
Set TPUSNAP_BENCH_BYTES to shrink the run below the default
baseline-scale 20 GB.

The state is **host-resident** (numpy): this benchmark measures the
framework pipeline — zero-copy serialization, budget-gated scheduling,
batched storage I/O — which is the part the framework controls. In this
environment the TPU chip is reached through a proxied PJRT tunnel whose
device→host link moves ~10 MB/s (measured; real v5e HBM→host DMA is
tens of GB/s), so including a device transfer would only measure the
tunnel. Device-array staging (async DtoH enqueued at prepare time,
overlapped with I/O) is exercised by tests/test_snapshot.py instead.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

# Reference: 20 GB / 13.91 s on 1×A100, local FS (BASELINE.md).
BASELINE_GBPS = 20.0 / 13.91

# Default = the baseline's own scale (20 GB, reference
# benchmarks/ddp/README.md:17) so vs_baseline compares like with like;
# TPUSNAP_BENCH_BYTES shrinks it for quick local runs.
TOTAL_BYTES = int(os.environ.get("TPUSNAP_BENCH_BYTES", 20 * 1024**3))
N_ARRAYS = 16
N_TAKE_RUNS = int(os.environ.get("TPUSNAP_BENCH_RUNS", 4))


def _drop_caches() -> bool:
    try:
        os.sync()
        with open("/proc/sys/vm/drop_caches", "w") as f:
            f.write("3")
        return True
    except OSError:
        return False


def measure_roofline(tmp: str, nbytes_per_file: int, n_files: int) -> float:
    """Raw aggregate write throughput for the snapshot's exact file
    layout: same native write engine, same 8-worker pool the fs plugin
    uses, same buffer alignment class as user state arrays (numpy
    allocations are not page-aligned), no snapshot machinery on top. This
    is the fastest any checkpoint writer could move these bytes with
    these durability semantics."""
    from tpusnap import _native as native

    # +16 offset: match the alignment class of numpy-owned state arrays
    # so the roofline exercises the same engine the take's writes do.
    buf = native.aligned_empty(nbytes_per_file + 16)[16:]
    # Random payload: constant fill could be flattered by host-side
    # image compression and would not match what the take writes.
    buf[:] = np.random.default_rng(1).integers(
        0, 255, nbytes_per_file, dtype=np.uint8
    )
    best = 0.0
    for _ in range(2):
        os.sync()
        ex = ThreadPoolExecutor(max_workers=8)
        t0 = time.perf_counter()
        list(
            ex.map(
                lambda i: native.write_file(os.path.join(tmp, f"r{i}"), buf),
                range(n_files),
            )
        )
        el = time.perf_counter() - t0
        ex.shutdown()
        for i in range(n_files):
            os.unlink(os.path.join(tmp, f"r{i}"))
        best = max(best, nbytes_per_file * n_files / el / 1e9)
    return best


def main() -> None:
    from tpusnap import PytreeState, Snapshot
    from tpusnap import scheduler as _sched
    from tpusnap import telemetry as _tele

    from tpusnap import _native as _natalloc

    per_array = TOTAL_BYTES // N_ARRAYS
    rng = np.random.default_rng(0)
    # DISTINCT resident buffers (the baseline checkpointed 20 GB of
    # real state; overlapping views would shrink the source working
    # set 16x and flatter every memory-bound pass), built at memcpy
    # speed: one RNG pass generates per_array random u16s, and each
    # array is that block rotated by i elements — pairwise-distinct
    # bytes, no aligned identical blocks for host-side
    # dedup/compression, ~20 s to build at 20 GB where np.roll+RNG per
    # array took ~5 min (THP-advised destinations fault at ~2.4 GB/s
    # vs ~0.17 for 4 KiB pages).
    raw = rng.integers(0, 2**16, per_array // 2, dtype=np.uint16)
    state = {}
    for i in range(N_ARRAYS):
        dst = _natalloc.empty_advised((per_array // 2,), np.uint16)
        dst[: per_array // 2 - i] = raw[i:]
        if i:
            dst[per_array // 2 - i :] = raw[:i]
        state[f"w{i}"] = dst.view(np.float16)
    nbytes = sum(a.nbytes for a in state.values())

    bench_root = tempfile.mkdtemp(prefix="tpusnap_bench_")
    try:
        # Restore first, from a single settled snapshot: the bench writes
        # ~20 GB overall, and the host keeps flushing guest writes for
        # many seconds after the guest's own sync returns — cold reads
        # measured in that window only show the host's writeback, not the
        # restore path.
        restore_snap = os.path.join(bench_root, "restore_src", "snap")
        Snapshot.take(restore_snap, {"model": PytreeState(state)})
        os.sync()
        time.sleep(8.0)

        import glob as _glob

        from tpusnap import _native as _nat

        def _paired_fraction_rounds(snap_path, pstate, rounds=5):
            """Interleaved like-for-like fraction pairs over one
            snapshot (VERDICT r4 #3: best-vs-best across disk windows
            produced unbounded, uninformative fractions). Each round
            measures, back to back in one disk window: prefaulted+CRC
            engine reads, then a warm-target restore — neither faults
            pages, both checksum every byte — whose ratio is
            restore_verified_fraction, the pipeline-efficiency number.
            (A fresh-target/fresh-buffer "cold" pair was tried and
            dropped: fresh-anon page faulting interacts with
            drop_caches so erratically that even adjacent samples
            disagree 100x; the cold restore is reported as an ABSOLUTE
            at full scale instead.) The median over rounds rides out a
            single mid-pair disk stall. Also bit-verifies the last
            warm restore against ``pstate``."""
            files = [
                f
                for f in _glob.glob(
                    os.path.join(snap_path, "**", "*"), recursive=True
                )
                if os.path.isfile(f)
                and not f.endswith(".snapshot_metadata")
                and ".tpusnap" not in f.split(os.sep)
            ]
            sizes = {f: os.path.getsize(f) for f in files}
            total = sum(sizes.values())
            pref = {f: np.empty(sizes[f], dtype=np.uint8) for f in files}
            for buf_ in pref.values():
                buf_[::4096] = 0  # fault every page once

            def engine_read_all(dests, want_crc=False) -> float:
                _drop_caches()

                def read_one(f):
                    n = sizes[f]
                    out = (
                        dests[f] if dests is not None else np.empty(n, np.uint8)
                    )
                    got, _, _ = _nat.read_range_into(
                        f, 0, n, out, want_crc=want_crc
                    )
                    assert got == n

                ex = ThreadPoolExecutor(max_workers=8)
                t0 = time.perf_counter()
                list(ex.map(read_one, files))
                el = time.perf_counter() - t0
                ex.shutdown()
                return total / el / 1e9

            pbytes = sum(a.nbytes for a in pstate.values())
            warm_t = {k: np.zeros_like(v) for k, v in pstate.items()}
            out = {
                "fracs_verified": [],
                "rooflines_verified": [],
                "warm_runs_s": [],
            }
            for _ in range(rounds):
                rl_v = engine_read_all(pref, want_crc=True)
                out["rooflines_verified"].append(rl_v)
                _drop_caches()
                t0 = time.perf_counter()
                Snapshot(snap_path).restore({"model": PytreeState(warm_t)})
                el = time.perf_counter() - t0
                out["warm_runs_s"].append(el)
                out["fracs_verified"].append((pbytes / el / 1e9) / rl_v)
            ks = sorted(pstate)
            out["verified_ok"] = all(
                np.array_equal(
                    warm_t[k].view(np.uint16), pstate[k].view(np.uint16)
                )
                for k in (ks[0], ks[-1])
            )
            return out

        # Untimed warmup restore: absorbs one-time costs (imports, native
        # lib load, allocator growth, residual host writeback of the
        # snapshot written above) so the timed runs measure the restore
        # path, not process startup. Reported, never counted.
        t0 = time.perf_counter()
        Snapshot(restore_snap).restore(
            {
                "model": PytreeState(
                    {f"w{i}": np.empty_like(state[f"w{i}"]) for i in range(N_ARRAYS)}
                )
            }
        )
        restore_warmup_s = time.perf_counter() - t0

        # Full-scale ABSOLUTES: warm-target (production resume-loop) and
        # fresh-target cold restores. No engine rooflines here — at
        # 20 GB a single sample spans minutes and the virtio disk
        # drifts several-fold within that, so no two full-scale
        # measurements share a window; fractions come from the tight
        # 2 GB probe below instead.
        restore_runs = []
        restore_warm_runs = []
        restore_summaries = []
        restore_probe_overheads = []
        warm_target = {
            f"w{i}": np.zeros_like(state[f"w{i}"]) for i in range(N_ARRAYS)
        }
        # In-restore read probes (TPUSNAP_PROBE, the read-lane mirror of
        # the in-take probes below): the cold restore's own scheduler
        # pauses its reads once per interval and measures the raw read
        # ceiling through the same plugin stack, so the summary's
        # restore_roofline_fraction shares every disk window with the
        # reads it judges. Probe cost is subtracted from the reported
        # restore time (restore_probe_overhead_s_runs publishes it).
        from tpusnap.knobs import override_probe as _override_probe_r

        r_probe_interval = max(256 * 1024 * 1024, TOTAL_BYTES // 8)
        r_probe_bytes = min(
            64 * 1024 * 1024, max(8 * 1024 * 1024, r_probe_interval // 8)
        )
        for _ in range(2):
            _drop_caches()
            t0 = time.perf_counter()
            Snapshot(restore_snap).restore({"model": PytreeState(warm_target)})
            restore_warm_runs.append(time.perf_counter() - t0)
            cold = _drop_caches()
            target = {
                f"w{i}": np.empty_like(state[f"w{i}"]) for i in range(N_ARRAYS)
            }
            app_state = {"model": PytreeState(target)}
            with _override_probe_r(
                True, interval_bytes=r_probe_interval, probe_bytes=r_probe_bytes
            ):
                t0 = time.perf_counter()
                Snapshot(restore_snap).restore(app_state)
                el_raw = time.perf_counter() - t0
            summary = _tele.LAST_RESTORE_SUMMARY or {}
            probe_elapsed = (summary.get("probe") or {}).get("elapsed_s") or 0.0
            restore_runs.append(max(el_raw - probe_elapsed, 1e-9))
            restore_probe_overheads.append(probe_elapsed)
            restore_summaries.append(summary)
        best_restore_i = min(
            range(len(restore_runs)), key=restore_runs.__getitem__
        )
        restore_el = restore_runs[best_restore_i]
        restore_gbps = nbytes / restore_el / 1e9
        # Restore-path telemetry of the BEST cold restore — the same
        # phase decomposition the take's stage_breakdown gives, so the
        # restore headline is diagnosable too (plan vs reads vs load).
        best_restore_summary = restore_summaries[best_restore_i] or {}
        restore_stage_breakdown = {
            "phases_s": {
                k: round(v, 3)
                for k, v in (best_restore_summary.get("phases") or {}).items()
            },
            "phase_coverage": best_restore_summary.get("phase_coverage"),
            "counters": {
                k: v
                for k, v in (best_restore_summary.get("counters") or {}).items()
                if not k.startswith("staging_pool.")
            },
        }
        # Bit-pattern comparison: random f16 buffers contain NaNs, and
        # NaN != NaN would fail a value comparison on correct data.
        ok = all(
            np.array_equal(
                app_state["model"].tree[f"w{i}"].view(np.uint16),
                state[f"w{i}"].view(np.uint16),
            )
            for i in (0, N_ARRAYS - 1)
        ) and all(
            np.array_equal(
                warm_target[f"w{i}"].view(np.uint16),
                state[f"w{i}"].view(np.uint16),
            )
            for i in (0, N_ARRAYS - 1)
        )
        del target, app_state, warm_target
        shutil.rmtree(os.path.join(bench_root, "restore_src"), ignore_errors=True)

        # Tight-window FRACTION probe (~2 GB: every sample is seconds,
        # so the paired samples genuinely share a disk window).
        def _build_probe_state():
            """Distinct-offset views into the random block (pairwise
            distinct bytes; probes only feed the fraction pairs, so
            the overlapping source footprint is fine here); lengths
            equalized and offsets clamped so the smallest TOTAL_BYTES
            still fits. ONE definition so the take and restore
            fraction probes can never desynchronize their scales."""
            per = min(TOTAL_BYTES, 2 * 1024**3) // N_ARRAYS
            plen = per // 2 - N_ARRAYS
            step = max(
                1, min(997, (len(raw) - plen) // max(N_ARRAYS - 1, 1))
            )
            return {
                f"w{i}": raw[i * step : i * step + plen].view(np.float16)
                for i in range(N_ARRAYS)
            }

        probe_state = _build_probe_state()
        probe_snap = os.path.join(bench_root, "fprobe", "snap")
        Snapshot.take(probe_snap, {"model": PytreeState(probe_state)})
        os.sync()
        fr = _paired_fraction_rounds(probe_snap, probe_state, rounds=5)
        ok = ok and fr["verified_ok"]
        shutil.rmtree(os.path.join(bench_root, "fprobe"), ignore_errors=True)
        restore_verified_fracs = fr["fracs_verified"]
        restore_rooflines_verified = fr["rooflines_verified"]

        # Full-scale fractions come from IN-TAKE INTERLEAVED PROBES
        # (TPUSNAP_PROBE): the take's own write scheduler pauses its I/O
        # once per interval and measures the raw engine ceiling through
        # the same plugin stack, seconds (not minutes) from the writes
        # it judges. This replaces the former separate roofline session
        # per run — at 20 GB that pair spanned minutes of drifting
        # virtio bandwidth and scattered the fraction 0.206–0.707
        # (ROADMAP 5a); the probe and the take now genuinely share
        # every disk window. Probe cost (~8 probes x PROBE_BYTES) is
        # subtracted from the reported take time (probe_overhead_s_runs
        # publishes what was subtracted).
        from tpusnap.knobs import override_probe
        from tpusnap.rss_profiler import measure_rss_deltas

        probe_interval = max(256 * 1024 * 1024, TOTAL_BYTES // 8)
        probe_bytes = min(64 * 1024 * 1024, max(8 * 1024 * 1024, probe_interval // 8))
        times = []
        splits = []
        rooflines = []
        take_fracs = []
        take_summaries = []
        probe_overheads = []
        budget_bytes = None
        for run in range(N_TAKE_RUNS):
            tmp = os.path.join(bench_root, f"take{run}")
            app_state = {"model": PytreeState(state)}
            # Drain pending page-cache writeback from earlier iterations so
            # each timed take competes only with its own I/O.
            os.sync()
            with override_probe(
                True, interval_bytes=probe_interval, probe_bytes=probe_bytes
            ):
                t0 = time.perf_counter()
                Snapshot.take(os.path.join(tmp, "snap"), app_state)
                el_raw = time.perf_counter() - t0
            summary = _tele.LAST_TAKE_SUMMARY or {}
            probe_info = summary.get("probe") or {}
            probe_elapsed = probe_info.get("elapsed_s") or 0.0
            el = max(el_raw - probe_elapsed, 1e-9)
            times.append(el)
            probe_overheads.append(probe_elapsed)
            # Runs whose probes failed (the runner stands down after
            # one failure) contribute None — kept IN the per-run lists
            # so cold_run_index keeps indexing every *_runs array, but
            # EXCLUDED from the aggregates (a 0.0 would read as a
            # catastrophic regression in roofline_gbps/..._fullscale
            # and the bench history event, when only the probe
            # hiccuped).
            ceiling = probe_info.get("write_gbps_p50")
            rooflines.append(ceiling)
            # The summary's own fraction: payload throughput over the
            # non-probe wall against the in-take ceiling.
            frac = summary.get("roofline_fraction")
            if frac is None and ceiling:
                frac = (nbytes / el / 1e9) / ceiling
            take_fracs.append(frac)
            stats = _sched.LAST_EXECUTION_STATS.get("write", {})
            budget_bytes = stats.get("budget_bytes") or budget_bytes
            splits.append(
                (stats.get("staging_s"), stats.get("total_s"))
            )
            take_summaries.append(summary)
            if run + 1 < N_TAKE_RUNS:
                shutil.rmtree(tmp, ignore_errors=True)
        best_i = min(range(len(times)), key=times.__getitem__)
        best = times[best_i]
        gbps = nbytes / best / 1e9
        staging_s, sched_total_s = splits[best_i]
        # None (not 0.0) when every run's probe failed: absent beats a
        # fake regression in the JSON and the history gate.
        roofline = max((r for r in rooflines if r), default=None)
        # Per-stage telemetry of the BEST take (tpusnap.telemetry): the
        # phase decomposition that makes the headline number diagnosable
        # — where the wall-clock went, not just how long it was.
        best_summary = take_summaries[best_i] or {}
        stage_breakdown = {
            "phases_s": {
                k: round(v, 3)
                for k, v in (best_summary.get("phases") or {}).items()
            },
            "phase_coverage": best_summary.get("phase_coverage"),
            "counters": {
                k: v
                for k, v in (best_summary.get("counters") or {}).items()
                if not k.startswith("staging_pool.")
            },
            "budget_high_water_gb": (
                round(
                    best_summary["gauges"]["scheduler.budget_used_bytes"] / 1e9, 2
                )
                if "scheduler.budget_used_bytes"
                in (best_summary.get("gauges") or {})
                else None
            ),
        }

        # Async-take leg at bench scale: the blocked window — under
        # PIPELINED staging this is the first-window clone pass, not the
        # full-state clone — and its peak RSS (bounded by the staging
        # window, not 1x state). The leg replaces the former sync-take
        # take_peak_rss_mb, which was pinned at ~0 by design (sync
        # takes of numpy state stage zero-copy views) and therefore
        # indistinguishable from a broken sampler — the async clone
        # path is the configuration where RSS MUST move, so the field
        # doubles as the sampler's self-check.
        #
        # Two takes: COLD (pool empty — the first window's clones pay
        # first-touch faulting; later windows already recycle the
        # buffers earlier writes released) and WARM (the steady-state
        # checkpoint loop: even window 0 reuses the previous take's
        # parked pages). The default 4 GiB pool covers the 2 GiB
        # default window with room — windowed staging is what made the
        # old state-sized pool override unnecessary.
        from tpusnap.knobs import get_async_stage_window_bytes

        try:
            async_blocked = []
            async_total = []
            rss_deltas = []
            for run in range(2):
                async_dir = os.path.join(
                    bench_root, f"async_take{run}", "snap"
                )
                os.sync()
                t0 = time.perf_counter()
                with measure_rss_deltas(rss_deltas):
                    pending = Snapshot.async_take(
                        async_dir, {"model": PytreeState(state)}
                    )
                    async_blocked.append(time.perf_counter() - t0)
                    pending.wait()
                async_total.append(time.perf_counter() - t0)
                shutil.rmtree(
                    os.path.dirname(async_dir), ignore_errors=True
                )
            async_peak_rss = max(rss_deltas, default=0)
            async_window_bytes = get_async_stage_window_bytes() or 0
        finally:
            from tpusnap import _staging_pool as _sp

            _sp.clear()  # release the window-sized pool

        # Beyond-reference capabilities, measured on the last snapshot:
        # an incremental take of the UNCHANGED state (all blobs dedup —
        # cost is one CRC pass, no storage I/O) and a full integrity
        # scrub (every stored byte re-read and verified).
        from tpusnap import verify_snapshot

        last_snap = os.path.join(
            bench_root, f"take{N_TAKE_RUNS - 1}", "snap"
        )
        # The incremental base records 64-bit dedup hashes
        # (TPUSNAP_RECORD_DEDUP_HASHES — the documented pattern for
        # bases of planned chains): skip decisions need 64-bit evidence
        # on both sides, and a plain base conservatively rewrites once.
        # Taken untimed so the headline take samples stay hash-lane-free.
        from tpusnap.knobs import override_record_dedup_hashes

        inc_base = os.path.join(bench_root, "inc_base", "snap")
        with override_record_dedup_hashes(True):
            Snapshot.take(inc_base, {"model": PytreeState(state)})
        os.sync()
        inc_path = os.path.join(bench_root, "inc", "snap")
        t0 = time.perf_counter()
        Snapshot.take(
            inc_path, {"model": PytreeState(state)}, incremental_from=inc_base
        )
        inc_take_s = time.perf_counter() - t0
        shutil.rmtree(os.path.join(bench_root, "inc_base"), ignore_errors=True)
        shutil.rmtree(os.path.join(bench_root, "inc"), ignore_errors=True)

        # Delta-mode section (tpusnap.delta): a short stream over a
        # "training loop" mutating ~1/64 of one array per step. Records
        # the steady-state realized RPO (max commit interval), delta
        # write amplification (delta bytes / changed bytes) and
        # per-micro-commit overhead — the numbers `history --check
        # --kind bench` regression-gates for the streaming mode.
        from tpusnap import slo as _slo_mod

        delta_root = os.path.join(bench_root, "delta_stream")
        d_state = {"model": PytreeState({"w0": state["w0"]})}
        d_arr = state["w0"].view(np.uint16)
        rows = d_arr.shape[0]
        delta_cadence_s = 0.5
        changed_bytes_total = 0
        stream = Snapshot.stream(
            delta_root, d_state, cadence_s=delta_cadence_s
        )
        t0 = time.perf_counter()
        step = 0
        while time.perf_counter() - t0 < 6.0:
            lo = (step * rows // 64) % rows
            hi = min(lo + rows // 64, rows)
            d_arr[lo:hi] ^= 1
            changed_bytes_total += d_arr[lo:hi].nbytes
            stream.mark_step(bytes_changed=int(d_arr[lo:hi].nbytes))
            step += 1
            time.sleep(0.01)
        stream.close(final_commit=False)
        delta_stats = dict(stream.stats)
        delta_rpo_s = _slo_mod.tracker().rpo_s()
        delta_write_amp = (
            delta_stats["bytes_written_total"] / changed_bytes_total
            if changed_bytes_total
            else None
        )
        shutil.rmtree(delta_root, ignore_errors=True)

        # Compression section (tpusnap.compress): compressed vs bypass
        # effective GB/s on a DETERMINISTIC bandwidth-constrained path —
        # the chaos plugin's write-path token bucket pins the pipe at
        # compress_throttle_gbps, the regime (cloud, virtio, tiered
        # remote drain) the codec exists for — plus the auto policy's
        # decision on both pipes: the throttled take must compress, the
        # local-fs take must bypass with wall within noise of
        # compression=off. State is bf16-precision f32 (mixed-precision
        # export shape): random u16 mantissa-truncated, so the shuffle
        # filter sees real entropy in the exponent planes and zeros in
        # the dropped ones — not an all-zeros softball.
        from tpusnap import compress as _comp_mod
        from tpusnap.knobs import override_compress

        c_rng = np.random.default_rng(7)
        c_arr = c_rng.standard_normal((192 << 20) // 4).astype(np.float32)
        c_arr = (c_arr.view(np.uint32) & np.uint32(0xFFFF0000)).view(
            np.float32
        )
        comp_nbytes = c_arr.nbytes
        comp_bw_gbps = 0.15
        comp_spec = f"transient_per_op=0,bandwidth_gbps={comp_bw_gbps}"
        comp_root = os.path.join(bench_root, "compress")

        def _comp_take(leg, mode, chaos):
            path = os.path.join(comp_root, leg, "snap")
            url = f"chaos+file://{path}" if chaos else path
            opts = {"fault_plan": comp_spec} if chaos else None
            with override_compress(mode=mode):
                t0 = time.perf_counter()
                Snapshot.take(
                    url,
                    {"model": PytreeState({"w": c_arr})},
                    storage_options=opts,
                )
                el = time.perf_counter() - t0
            stored = sum(
                os.path.getsize(os.path.join(r, f))
                for r, _, fs in os.walk(path)
                for f in fs
                if not f.endswith(".snapshot_metadata")
                and ".tpusnap" not in r.split(os.sep)
            )
            decision = _comp_mod.LAST_DECISION
            shutil.rmtree(os.path.join(comp_root, leg), ignore_errors=True)
            return el, stored, decision

        comp_off_s, _, _ = _comp_take("off", "off", chaos=True)
        comp_on_s, comp_stored, _ = _comp_take("on", "on", chaos=True)
        # Auto on the throttled pipe: a fresh ceiling registry forces
        # the policy mini-probe THROUGH the throttled plugin stack, so
        # the decision comes from a live measurement of this pipe (the
        # full-scale takes above already fed the registry the REAL
        # local-fs ceiling under the same innermost label).
        _comp_mod._reset_ceilings()
        comp_auto_s, _, comp_auto_dec = _comp_take("auto", "auto", chaos=True)
        _comp_mod._reset_ceilings()
        # Local fs, auto-vs-off: best-of-3 per side (192 MiB local
        # takes are sub-second; a single sample's page-cache/writeback
        # jitter exceeds the 5% acceptance band being measured).
        local_auto_runs, local_off_runs = [], []
        local_auto_dec = None
        for _ in range(3):
            el, _, d = _comp_take("lauto", "auto", chaos=False)
            local_auto_runs.append(el)
            local_auto_dec = d
            el, _, _ = _comp_take("loff", "off", chaos=False)
            local_off_runs.append(el)
        shutil.rmtree(comp_root, ignore_errors=True)
        compress_section = {
            "compress_codec_gbps": round(_comp_mod.codec_throughput_gbps(), 3),
            "compress_throttle_gbps": comp_bw_gbps,
            "compress_section_gb": round(comp_nbytes / 1024**3, 2),
            "compress_ratio": round(comp_nbytes / comp_stored, 3),
            "compress_effective_gbps": round(
                comp_nbytes / comp_on_s / 1e9, 3
            ),
            "compress_bypass_gbps": round(comp_nbytes / comp_off_s / 1e9, 3),
            # The headline: effective throughput multiplier from
            # compressing on the bandwidth-bound path (acceptance:
            # >= 1.5x for this bf16/f32 state).
            "compress_vs_bypass": round(comp_off_s / comp_on_s, 3),
            "compress_auto_throttled_s": round(comp_auto_s, 2),
            "compress_auto_decision_throttled": (
                comp_auto_dec.to_meta()["decision"] if comp_auto_dec else None
            ),
            "compress_auto_reason_throttled": (
                comp_auto_dec.reason if comp_auto_dec else None
            ),
            "compress_auto_decision_local": (
                local_auto_dec.to_meta()["decision"] if local_auto_dec else None
            ),
            "compress_auto_reason_local": (
                local_auto_dec.reason if local_auto_dec else None
            ),
            "compress_auto_local_wall_s": round(min(local_auto_runs), 3),
            "compress_off_local_wall_s": round(min(local_off_runs), 3),
            # Acceptance: <= 1.05 — auto's bypass decision costs ~no
            # wall on a pipe that outruns the codec.
            "compress_auto_local_overhead": round(
                min(local_auto_runs) / min(local_off_runs), 3
            ),
        }

        # Scrub, interleaved with its own roofline: the exact byte ranges
        # the scrub verifies, read through the same native fused read+CRC
        # engine at the same concurrency, zero manifest/asyncio machinery.
        # r03 published a single scrub sample with no roofline and the
        # driver caught it 9x low (0.347 vs 3.0 GB/s) — competing with the
        # writeback of the take that preceded it; the sync + interleaved
        # sampling below makes the number self-verifying.
        from tpusnap.inspect import iter_blobs, load_snapshot_metadata
        from tpusnap.knobs import get_scrub_concurrency

        os.sync()
        # Settle: the guest's sync returns before the HOST finishes
        # absorbing the take section's writeback; cold reads in that
        # window measure the host's flush, not the scrub (same reason
        # the restore section runs first from a settled snapshot).
        time.sleep(8.0)
        def _scrub_ranges_of(snap_path):
            manifest = load_snapshot_metadata(snap_path).manifest
            ranges = []  # (abs_path, offset, nbytes)
            for b in iter_blobs(manifest):
                off, end = b.byte_range if b.byte_range else (0, None)
                if end is None:
                    end = os.path.getsize(
                        os.path.join(snap_path, b.location)
                    )
                ranges.append(
                    (os.path.join(snap_path, b.location), off, end - off)
                )
            return ranges

        def _scrub_roofline_once(ranges) -> float:
            _drop_caches()
            n_slots = get_scrub_concurrency()
            scratch = max(n for _, _, n in ranges)
            total = sum(n for _, _, n in ranges)
            local = __import__("threading").local()

            def read_one(rng):
                path_, off_, n_ = rng
                buf = getattr(local, "buf", None)
                if buf is None or buf.nbytes < n_:
                    buf = _nat.aligned_empty(max(n_, scratch))
                    local.buf = buf
                got, _, _ = _nat.read_range_into(
                    path_, off_, n_, memoryview(buf)[:n_], want_crc=True
                )
                assert got == n_

            ex = ThreadPoolExecutor(max_workers=n_slots)
            t0 = time.perf_counter()
            list(ex.map(read_one, ranges))
            el = time.perf_counter() - t0
            ex.shutdown()
            return total / el / 1e9

        scrub_ranges = _scrub_ranges_of(last_snap)
        scrub_bytes = sum(n for _, _, n in scrub_ranges)
        scrub_runs = []
        scrub_rooflines = []
        scrub_fullscale_fracs = []
        scrub_clean = True
        for _ in range(2):
            rl_fs = _scrub_roofline_once(scrub_ranges)
            scrub_rooflines.append(rl_fs)
            _drop_caches()
            t0 = time.perf_counter()
            scrub_report = verify_snapshot(last_snap)
            el_fs = time.perf_counter() - t0
            scrub_runs.append(el_fs)
            scrub_fullscale_fracs.append(
                (scrub_bytes / el_fs / 1e9) / rl_fs
            )
            scrub_clean = scrub_clean and scrub_report.clean
        scrub_s = min(scrub_runs)
        scrub_roofline = max(scrub_rooflines)

        # ---- tight-window fraction probe: take + scrub ----
        # Same reasoning as the restore fractions: at full scale a
        # single sample spans minutes and host contention drifts
        # several-fold within a pair, so the FRACTIONS come from ~2 GB
        # samples that take seconds; the full-scale runs above are the
        # absolutes (their per-run fractions are published as a
        # diagnostic list).
        fprobe_dir = os.path.join(bench_root, "take_fprobe")
        os.makedirs(fprobe_dir, exist_ok=True)
        tp_state = _build_probe_state()
        tp_file_bytes = next(iter(tp_state.values())).nbytes
        tp_nbytes = sum(a.nbytes for a in tp_state.values())
        take_probe_fracs = []
        tp_snap = None
        for r in range(5):
            rl = measure_roofline(fprobe_dir, tp_file_bytes, N_ARRAYS)
            tp_snap = os.path.join(fprobe_dir, f"t{r}", "snap")
            os.sync()
            t0 = time.perf_counter()
            Snapshot.take(tp_snap, {"model": PytreeState(tp_state)})
            el = time.perf_counter() - t0
            take_probe_fracs.append((tp_nbytes / el / 1e9) / rl)
            if r + 1 < 5:
                shutil.rmtree(os.path.dirname(tp_snap), ignore_errors=True)
        os.sync()
        time.sleep(4.0)
        tp_ranges = _scrub_ranges_of(tp_snap)
        tp_bytes = sum(n for _, _, n in tp_ranges)
        scrub_probe_fracs = []
        for _ in range(3):
            rl = _scrub_roofline_once(tp_ranges)
            _drop_caches()
            t0 = time.perf_counter()
            rep = verify_snapshot(tp_snap)
            el = time.perf_counter() - t0
            scrub_clean = scrub_clean and rep.clean
            scrub_probe_fracs.append((tp_bytes / el / 1e9) / rl)
        shutil.rmtree(fprobe_dir, ignore_errors=True)

        # pinned_host (UVM analog) capability probe on the REAL backend,
        # via the wedge-proof runner (own process group, no inherited
        # pipes, group SIGKILL on timeout, one retry) — round 4's
        # subprocess.run(capture_output=...) version blocked draining
        # pipes a surviving tunnel helper held open and lost the leg.
        from tpusnap._subproc import run_hard_timeout

        probe_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks",
            "pinned_host",
            "probe.py",
        )
        health_code = (
            "import json, time, jax, numpy as np, jax.numpy as jnp\n"
            "t0 = time.perf_counter()\n"
            "d = jax.devices()[0]\n"
            "np.asarray(jax.device_put(jnp.ones(1 << 16, jnp.float32), d))\n"
            "print(json.dumps({'platform': d.platform,"
            " 's': round(time.perf_counter() - t0, 2)}))\n"
        )
        try:
            # Fast health gate first: a dead tunnel must cost the bench
            # ~90s with the cause recorded, not 2x the full probe
            # timeout. 45s per attempt covers cold PJRT init (measured
            # 12.6s through the tunnel incl. jax startup); the retry
            # keeps a healthy-but-cold backend from being falsely
            # declared dead by one slow first attempt.
            health = run_hard_timeout(
                [sys.executable, "-c", health_code], timeout_s=45, retries=1
            )
            if health.timed_out or health.returncode != 0:
                pinned_host = {
                    "ok": False,
                    "skipped": True,
                    "error": (
                        "tunnel unhealthy: 45s device-roundtrip probe "
                        + (
                            f"timed out ({health.attempts} attempts)"
                            if health.timed_out
                            else f"rc={health.returncode}: {health.stderr[-200:]}"
                        )
                    ),
                }
            else:
                r = run_hard_timeout(
                    [sys.executable, probe_path], timeout_s=150, retries=1
                )
                if r.timed_out:
                    pinned_host = {
                        "ok": False,
                        "error": "timeout (TPU tunnel hang)",
                        "attempts": r.attempts,
                    }
                else:
                    lines = [
                        ln for ln in r.stdout.strip().splitlines() if ln.strip()
                    ]
                    pinned_host = (
                        json.loads(lines[-1])
                        if lines
                        else {
                            "ok": False,
                            "error": f"rc={r.returncode}: {r.stderr[-200:]}",
                        }
                    )
        except Exception as e:  # noqa: BLE001
            pinned_host = {"ok": False, "error": str(e)}
    finally:
        shutil.rmtree(bench_root, ignore_errors=True)

    # Warm-only views of the full-scale run arrays: run 0 is the COLD
    # run of its section (first take at full scale faults/evicts the
    # page-cache working set the later runs inherit — r05's 0.206
    # first-run outlier in roofline_fraction_fullscale_runs), so trend
    # tooling should read the warm aggregates and treat runs[cold_run_index]
    # as warmup, not regression. The cross-run history applies the same
    # rule via its cold tag.
    def _warm(vals):
        return vals[1:] if len(vals) > 1 else vals

    # Aggregation views of the per-run fraction list: None entries are
    # failed-probe runs (kept in the *_runs arrays for index alignment
    # with cold_run_index, excluded from every aggregate).
    _fracs_valid = [f for f in take_fracs if f is not None]
    _warm_fracs_valid = [f for f in _warm(take_fracs) if f is not None]

    result = {
        "metric": "snapshot_take_local_fs",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
        "roofline_gbps": round(roofline, 3) if roofline else None,
        # Median of same-round take/roofline pairs from the
        # tight ~2 GB probe (seconds per sample, so the pair
        # genuinely shares a host/disk window; full-scale
        # pairs span minutes and drift several-fold — their
        # fractions are published below as a diagnostic).
        "roofline_fraction": round(
            statistics.median(take_probe_fracs), 3
        ),
        "roofline_fraction_probe_gb": round(
            min(TOTAL_BYTES, 2 * 1024**3) / 1024**3, 2
        ),
        "roofline_fraction_runs": [
            round(f, 3) for f in take_probe_fracs
        ],
        # Full-scale fractions from IN-TAKE INTERLEAVED PROBES
        # (TPUSNAP_PROBE through the take's own scheduler): each
        # take self-measures its engine ceiling seconds from the
        # writes it judges, so the fraction is immune to the
        # multi-minute disk drift that made the former separate
        # roofline session scatter 0.206–0.707 (see
        # BENCHMARKS.md "Round 7 protocol change").
        "roofline_fullscale_source": "intake_probes",
        # Failed-probe runs publish null at their index (every *_runs
        # array stays aligned with take_runs_s and cold_run_index) and
        # are excluded from the aggregates.
        "roofline_fraction_fullscale": (
            round(statistics.median(_fracs_valid), 3)
            if _fracs_valid
            else None
        ),
        "roofline_fraction_fullscale_runs": [
            round(f, 3) if f is not None else None for f in take_fracs
        ],
        "probe_write_gbps_runs": [
            round(r, 3) if r is not None else None for r in rooflines
        ],
        "probe_overhead_s_runs": [
            round(p, 2) for p in probe_overheads
        ],
        "probe_interval_gb": round(probe_interval / 1024**3, 2),
        "probe_bytes_mb": round(probe_bytes / 1024**2, 1),
        # Index of the cold-cache run in every *_runs array of
        # this JSON (the section's first run), plus warm-only
        # aggregates so trend tooling doesn't flag warmup.
        "cold_run_index": 0,
        "roofline_fraction_fullscale_warm": (
            round(statistics.median(_warm_fracs_valid), 3)
            if _warm_fracs_valid
            else None
        ),
        # Since round 7 these are the in-take probe ceilings (the
        # name kept for BENCH_r01-r06 trend comparability; null at a
        # failed-probe run's index).
        "roofline_runs_gbps": [
            round(r, 3) if r is not None else None for r in rooflines
        ],
        "take_runs_s": [round(t, 2) for t in times],
        "take_warm_best_s": round(min(_warm(times)), 2),
        "stage_breakdown": stage_breakdown,
        "staging_s": round(staging_s, 2) if staging_s else None,
        "residual_io_s": (
            round(sched_total_s - staging_s, 2)
            if staging_s and sched_total_s
            else None
        ),
        # RESTORE HEADLINES are the verified-fraction pair below:
        # the fraction (pipeline efficiency, like-for-like paired
        # samples) and the warm absolute (the production
        # resume-loop). The cold absolute was demoted to
        # restore_cold_gbps (ROADMAP 5d): a 20 GB cold sample
        # spans minutes of drifting virtio bandwidth and page-cache
        # state, so it reads as a disk-weather report, not a
        # pipeline verdict.
        "restore_verified_fraction": round(
            statistics.median(restore_verified_fracs), 3
        ),
        "restore_warm_gbps": round(
            nbytes / min(restore_warm_runs) / 1e9, 3
        ),
        "restore_cold_gbps": round(restore_gbps, 3),
        # Deprecated alias of restore_cold_gbps, kept so BENCH_r01-r05
        # trend tooling and the cross-run history stay comparable.
        "restore_gbps": round(restore_gbps, 3),
        "restore_verified_fraction_runs": [
            round(f, 3) for f in restore_verified_fracs
        ],
        "restore_roofline_verified_runs_gbps": [
            round(r, 3) for r in restore_rooflines_verified
        ],
        "restore_runs_s": [round(t, 2) for t in restore_runs],
        # Drift-immune read-path fraction of the BEST cold restore:
        # payload read throughput over the non-probe wall against the
        # in-restore probe ceiling (same window, same plugin stack).
        # None when the probe failed or stood down.
        "restore_roofline_fraction": best_restore_summary.get(
            "restore_roofline_fraction"
        ),
        "restore_probe_read_gbps": (
            best_restore_summary.get("probe") or {}
        ).get("read_gbps_p50"),
        "restore_probe_overhead_s_runs": [
            round(o, 3) for o in restore_probe_overheads
        ],
        "restore_stage_breakdown": restore_stage_breakdown,
        "restore_warm_runs_s": [
            round(t, 2) for t in restore_warm_runs
        ],
        "restore_warmup_s": round(restore_warmup_s, 2),
        "restore_cold_cache": cold,
        "restore_verified": ok,
        # Warm = the steady-state checkpoint loop (pool pages
        # reused); cold = first take of the process. Under pipelined
        # staging the blocked window is O(stage window), not O(state).
        "async_take_blocked_s": round(async_blocked[-1], 2),
        "async_take_blocked_cold_s": round(async_blocked[0], 2),
        "async_take_total_s": round(async_total[-1], 2),
        "async_stage_window_gb": round(async_window_bytes / 1e9, 2),
        # Sync/async crossover, both sides from this run: the blocked
        # window is blocked_vs_sync of a sync take (training-visible
        # cost ratio), and async is the net win whenever the training
        # work overlapped with the background drain exceeds
        # breakeven_overlap_s (the drain's wall-clock excess over a
        # sync take). See BENCHMARKS.md "Sync/async crossover".
        "async_blocked_vs_sync_take": round(
            async_blocked[-1] / min(_warm(times)), 4
        ),
        "async_breakeven_overlap_s": round(
            max(async_total[-1] - min(_warm(times)), 0.0), 2
        ),
        # Clone-path RSS: must be >> 0 (the windowed clones are real
        # allocations) but BOUNDED by the staging window — no longer
        # ~1x state; still the RSS sampler's self-check, unlike the
        # sync take whose zero-copy staging pinned the old
        # take_peak_rss_mb at 0.
        "async_take_peak_rss_mb": round(async_peak_rss / 1e6),
        "memory_budget_gb": (
            round(budget_bytes / 1e9, 2) if budget_bytes else None
        ),
        "incremental_take_s": round(inc_take_s, 2),
        "incremental_effective_gbps": round(
            nbytes / inc_take_s / 1e9, 3
        ),
        # Delta streaming mode (tpusnap.delta): realized RPO in the
        # steady state (max interval between micro-commits — the
        # headline the stream exists to shrink; configured cadence
        # alongside for the ratio), write amplification (delta bytes
        # written / bytes actually mutated; tile-grain dedup keeps it
        # ~1), and per-micro-commit overhead (the dual-hash pass +
        # changed-tile writes).
        "delta_cadence_s": delta_cadence_s,
        "delta_commits": delta_stats["commits"],
        "delta_rpo_seconds": delta_stats["max_commit_interval_s"],
        "delta_rpo_at_close_s": round(delta_rpo_s, 3),
        "delta_write_amplification": (
            round(delta_write_amp, 3) if delta_write_amp else None
        ),
        "delta_commit_overhead_s": delta_stats["last_commit_wall_s"],
        "delta_bytes_written": delta_stats["bytes_written_total"],
        "delta_compactions": delta_stats["compactions"],
        "scrub_s": round(scrub_s, 2),
        "scrub_gbps": round(scrub_bytes / scrub_s / 1e9, 3),
        "scrub_roofline_gbps": round(scrub_roofline, 3),
        # Median of same-round pairs from the tight probe.
        "scrub_roofline_fraction": round(
            statistics.median(scrub_probe_fracs), 3
        ),
        "scrub_roofline_fraction_runs": [
            round(f, 3) for f in scrub_probe_fracs
        ],
        "scrub_roofline_fraction_fullscale_runs": [
            round(f, 3) for f in scrub_fullscale_fracs
        ],
        "scrub_roofline_fraction_fullscale_warm": round(
            statistics.median(_warm(scrub_fullscale_fracs)), 3
        ),
        "scrub_runs_gbps": [
            round(scrub_bytes / t / 1e9, 3) for t in scrub_runs
        ],
        "scrub_roofline_runs_gbps": [
            round(r, 3) for r in scrub_rooflines
        ],
        "scrub_clean": scrub_clean,
        # Fused tile compression (tpusnap.compress): measured on its own
        # bf16-precision state over a deterministic token-bucket pipe —
        # see "Compression section" above for leg semantics.
        **compress_section,
        "pinned_host": pinned_host,
    }

    # Checkpoint-SLO accuracy check (tpusnap.slo), free with every bench
    # run: the RTO estimator grades itself against the restore this very
    # run measured (the bench's own takes/restores fed history and the
    # tracker's commit anchor above), and the realized commit interval
    # rides along — `history --kind bench` then trends estimator drift.
    try:
        from tpusnap import slo as _slo

        _est = _slo.estimate_rto(nbytes)
        _slo_state = _slo.tracker().snapshot_state()
        result["slo_estimated_rto_s"] = _est.seconds if _est.ok else None
        result["slo_rto_actual_s"] = round(restore_el, 3)
        result["slo_rto_ratio"] = (
            round(_est.seconds / restore_el, 3)
            if _est.ok and restore_el > 0
            else None
        )
        result["slo_commit_interval_s"] = _slo_state.get("commit_interval_s")
    except Exception:
        pass

    # Record the headline trajectory into the same cross-run history the
    # takes/restores above already fed (kind="take"/"restore", first run
    # cold-tagged automatically) — BENCH_r*.json trajectories become
    # queryable by `python -m tpusnap history --kind bench [--check]`.
    try:
        from tpusnap import history as _hist

        # Tail-latency gate feed: p99/p50 storage-write latency of the
        # best take's log2 histograms (event_from_summary derives the
        # same fields take events carry, so `history --check --kind
        # bench --metric storage_write_p99_s` gates like-for-like).
        _hist_fields = _hist.event_from_summary("bench", best_summary or {})
        # Read-path trend feed from the best cold restore's summary:
        # storage_read_p50_s/p99_s gate tail read latency upward and
        # restore_roofline_fraction/probe_read_gbps trend the read-lane
        # pipeline efficiency, like-for-like with restore events.
        _hist_restore = _hist.event_from_summary(
            "bench", best_restore_summary or {}
        )
        _hist.record_event(
            {
                "v": 1,
                "ts": round(time.time(), 3),
                "kind": "bench",
                "rank": 0,
                "world_size": 1,
                "bytes": nbytes,
                "wall_s": round(best, 3),
                "throughput_gbps": round(gbps, 3),
                **{
                    k: _hist_fields[k]
                    for k in (
                        "storage_write_p50_s",
                        "storage_write_p99_s",
                        "probe_write_gbps",
                    )
                    if k in _hist_fields
                },
                "roofline_fraction": result["roofline_fraction"],
                "roofline_fraction_fullscale": result[
                    "roofline_fraction_fullscale"
                ],
                "roofline_fraction_fullscale_warm": result[
                    "roofline_fraction_fullscale_warm"
                ],
                "restore_gbps": result["restore_gbps"],
                "restore_verified_fraction": result[
                    "restore_verified_fraction"
                ],
                **{
                    k: _hist_restore[k]
                    for k in (
                        "storage_read_p50_s",
                        "storage_read_p99_s",
                        "restore_roofline_fraction",
                        "probe_read_gbps",
                    )
                    if k in _hist_restore
                },
                "async_take_blocked_s": result["async_take_blocked_s"],
                "async_take_peak_rss_mb": result["async_take_peak_rss_mb"],
                "scrub_gbps": result["scrub_gbps"],
                "incremental_effective_gbps": result[
                    "incremental_effective_gbps"
                ],
                # Streaming-mode regression feed: `history --check
                # --kind bench --metric delta_rpo_seconds` gates the
                # realized RPO upward like any duration, and the
                # amplification/overhead columns trend alongside.
                **{
                    k: result[k]
                    for k in (
                        "delta_rpo_seconds",
                        "delta_write_amplification",
                        "delta_commit_overhead_s",
                    )
                    if result.get(k) is not None
                },
                # Compression regression feed: `history --check --kind
                # bench --metric compress_effective_gbps` gates the
                # bandwidth-bound win downward like every throughput,
                # and the recorded auto decisions make a policy flip
                # (compress where it should bypass, or vice versa)
                # visible in the trend without rereading BENCH JSONs.
                **{
                    k: result[k]
                    for k in (
                        "compress_effective_gbps",
                        "compress_bypass_gbps",
                        "compress_vs_bypass",
                        "compress_ratio",
                        "compress_codec_gbps",
                        "compress_auto_decision_throttled",
                        "compress_auto_decision_local",
                        "compress_auto_local_overhead",
                    )
                    if result.get(k) is not None
                },
                # Estimator-vs-measured: slo_rto_ratio near 1.0 means
                # the RTO gauge can be trusted; `history --check --kind
                # bench --metric slo_rto_actual_s` gates restore time
                # upward like every other duration.
                **{
                    k: result[k]
                    for k in (
                        "slo_estimated_rto_s",
                        "slo_rto_actual_s",
                        "slo_rto_ratio",
                        "slo_commit_interval_s",
                    )
                    if result.get(k) is not None
                },
            }
        )
    except Exception:
        pass

    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
