"""Headline benchmark: Snapshot.take throughput to local FS.

Mirrors the reference's published benchmark (single-accelerator DDP take
to local FS, /root/reference/benchmarks/ddp/README.md:17 — 20 GB in
~13.91 s ≈ 1.438 GB/s on one A100; DtoH over PCIe is not the bottleneck
there, storage I/O is). ``vs_baseline`` is the throughput ratio against
that 1.438 GB/s.

The state is **host-resident** (numpy): this benchmark measures the
framework pipeline — zero-copy serialization, budget-gated scheduling,
batched storage I/O — which is the part the framework controls. In this
environment the TPU chip is reached through a proxied PJRT tunnel whose
device→host link moves ~10 MB/s (measured; real v5e HBM→host DMA is
tens of GB/s), so including a device transfer would only measure the
tunnel. Device-array staging (async DtoH enqueued at prepare time,
overlapped with I/O) is exercised by tests/test_snapshot.py instead.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

# Reference: 20 GB / 13.91 s on 1×A100, local FS (BASELINE.md).
BASELINE_GBPS = 20.0 / 13.91

TOTAL_BYTES = int(os.environ.get("TPUSNAP_BENCH_BYTES", 2 * 1024**3))
N_ARRAYS = 16


def main() -> None:
    from tpusnap import PytreeState, Snapshot

    per_array = TOTAL_BYTES // N_ARRAYS
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 2**16, per_array // 2, dtype=np.uint16)
    state = {
        # distinct buffers (shifted views copied) so no write dedups
        f"w{i}": np.roll(raw, i).view(np.float16)
        for i in range(N_ARRAYS)
    }
    nbytes = sum(a.nbytes for a in state.values())

    times = []
    for _ in range(3):
        tmp = tempfile.mkdtemp(prefix="tpusnap_bench_")
        try:
            app_state = {"model": PytreeState(state)}
            # Drain pending page-cache writeback from earlier iterations so
            # each timed take competes only with its own I/O.
            os.sync()
            t0 = time.perf_counter()
            Snapshot.take(os.path.join(tmp, "snap"), app_state)
            times.append(time.perf_counter() - t0)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    best = min(times)
    gbps = nbytes / best / 1e9
    print(
        json.dumps(
            {
                "metric": "snapshot_take_local_fs",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / BASELINE_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
