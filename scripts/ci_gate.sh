#!/usr/bin/env bash
# One-entrypoint CI/cron gate for tpusnap:
#
#   1. `tpusnap lint --check` — AST invariant checker over the package
#      (knob access, monotonic clocks, sidecar constants, silent
#      swallows, async blocking calls, finalizer joins, knob/doc
#      drift); runs first because it is the cheapest gate
#   2. tier-1 tests (the ROADMAP.md verify command), run with
#      TPUSNAP_LOCKCHECK=1 by conftest — any lock-order cycle fails
#      the session
#   3. `tpusnap history --check` — cross-run regression gate on this
#      host's history.jsonl: take throughput AND p99 storage-write
#      latency (insufficient history — exit 3 — passes, so a fresh
#      host bootstraps instead of failing forever)
#   4. `tpusnap analyze --check` — performance doctor on the newest
#      bench/CI snapshot (tail latency, stragglers, roofline), when
#      one is available
#
# Usage:
#   scripts/ci_gate.sh [SNAPSHOT_PATH]
#
#   SNAPSHOT_PATH        snapshot for step 4 (default: $TPUSNAP_CI_SNAPSHOT,
#                        else step 4 is skipped with a note)
#   TPUSNAP_CI_SKIP_TESTS=1   skip step 2 (cron boxes that only gate
#                             perf trends, not code)
#
# Exit: non-zero on the first failing gate, echoing which one.

set -u -o pipefail

cd "$(dirname "$0")/.."

fail() { echo "ci_gate: FAIL — $1" >&2; exit "$2"; }

# ---- 1. static analysis --------------------------------------------------
echo "ci_gate: [1/4] lint --check (AST invariants)"
env JAX_PLATFORMS=cpu python -m tpusnap lint --check
rc=$?
[ "$rc" -eq 0 ] || fail "tpusnap lint --check (rc=$rc)" "$rc"

# ---- 2. tier-1 -----------------------------------------------------------
if [ "${TPUSNAP_CI_SKIP_TESTS:-0}" != "1" ]; then
    echo "ci_gate: [2/4] tier-1 tests"
    rm -f /tmp/_t1.log
    timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
    rc=${PIPESTATUS[0]}
    echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
    [ "$rc" -eq 0 ] || fail "tier-1 tests (rc=$rc)" "$rc"
else
    echo "ci_gate: [2/4] tier-1 tests skipped (TPUSNAP_CI_SKIP_TESTS=1)"
fi

# ---- 3. cross-run history gate ------------------------------------------
echo "ci_gate: [3/4] history --check (throughput + p99 write latency)"
for kind in take bench; do
    python -m tpusnap history --check --kind "$kind" \
        --metric throughput_gbps --metric storage_write_p99_s --json
    rc=$?
    case "$rc" in
        0) echo "ci_gate: history[$kind] OK" ;;
        3) echo "ci_gate: history[$kind] insufficient comparable history (bootstrapping) — pass" ;;
        *) fail "history --check --kind $kind regressed (rc=$rc)" "$rc" ;;
    esac
done

# ---- 4. analyze doctor on the latest snapshot ---------------------------
SNAP="${1:-${TPUSNAP_CI_SNAPSHOT:-}}"
if [ -n "$SNAP" ]; then
    echo "ci_gate: [4/4] analyze --check $SNAP"
    python -m tpusnap analyze --check --history "$SNAP"
    rc=$?
    case "$rc" in
        0) echo "ci_gate: analyze OK" ;;
        3) echo "ci_gate: analyze found no telemetry in $SNAP — pass (knob-off take)" ;;
        *) fail "analyze --check $SNAP (rc=$rc)" "$rc" ;;
    esac
else
    echo "ci_gate: [4/4] analyze skipped (no snapshot; pass a path or set TPUSNAP_CI_SNAPSHOT)"
fi

echo "ci_gate: PASS"
