#!/usr/bin/env bash
# One-entrypoint CI/cron gate for tpusnap:
#
#   1. `tpusnap lint --check` — AST invariant checker over the package
#      (knob access, monotonic clocks, sidecar constants, silent
#      swallows, async blocking calls, finalizer joins, knob/doc
#      drift); runs first because it is the cheapest gate
#   2. tier-1 tests (the ROADMAP.md verify command), run with
#      TPUSNAP_LOCKCHECK=1 by conftest — any lock-order cycle fails
#      the session
#   3. `tpusnap history --check` — cross-run regression gate on this
#      host's history.jsonl: take throughput AND p99 storage-write
#      latency (insufficient history — exit 3 — passes, so a fresh
#      host bootstraps instead of failing forever)
#   4. `tpusnap analyze --check` — performance doctor on the newest
#      bench/CI snapshot (tail latency, stragglers, roofline), when
#      one is available
#   5. `tpusnap slo --check` smoke — checkpoint-SLO gate exit contract:
#      0 on a healthy fresh commit, 2 on a seeded stale-commit breach,
#      3 on an empty telemetry dir (no records)
#   6. delta soak smoke — `Snapshot.stream` against a training loop
#      for ~30 s with TPUSNAP_SLO_RPO_S armed: `tpusnap slo --check`
#      must exit 0 and the measured steady-state RPO (max micro-commit
#      interval) must be ≤ 2x the configured cadence; then a second
#      soak is SIGKILLed inside a micro-commit and the torn tail must
#      honor the chain exit contracts (member fsck exit 4 naming the
#      torn delta micro-commit, root fsck exit 4, timeline exit 4/3)
#   7. `tpusnap timeline` smoke — take → SIGKILL → timeline must honor
#      its exit contract: 0 on a committed path, post-mortem section +
#      exit 4 on a torn one, exit 3 when no flight data exists
#      (matching the trace/analyze zero-span contract)
#   8. write-back tiering smoke — a tiered take against a chaos-wrapped
#      remote commits locally (fsck: local-committed), a drain is
#      killed mid-upload (SIGKILL), the resumed `tpusnap drain`
#      converges to remote-durable skipping journal-proven blobs, and
#      the `fsck`/`drain` exit contracts hold at each state; hermetic
#      like the timeline/slo smokes
#   9. fused-compression smoke — a forced-compressed take must scrub
#      clean and restore bit-exact, the auto policy must bypass against
#      a pinned-fast pipe ceiling (codec-free manifest; pinned so the
#      gate tests the policy, not this runner's disk weather) and
#      choose compress against the chaos token-bucket throttle, and the
#      throttled compressed snapshot must restore bit-exact; hermetic
#      like the timeline/slo/tiering smokes (SIGKILL-mid-compressed-
#      take salvage lives in tier-1: tests/test_compress.py; the
#      measured local-disk bypass claim lives in bench.py)
#  10. rank-failure smoke — a 2-process take whose rank 1 is SIGKILLed
#      by a rank-scoped chaos plan (`rank=1,crash_after_op=write:1`)
#      must fail on the survivor with RankFailedError naming the dead
#      rank within seconds (lease liveness, not the 600 s barrier
#      timeout); a second 2-process fully-replicated take under
#      TPUSNAP_RANK_FAILURE=degrade must COMMIT on the survivor, scrub
#      clean, restore bit-exact, and record the adoption in
#      extras["degraded"]; hermetic like the other smokes
#  11. elastic-stream smoke — the ISSUE 16 acceptance scenarios as a
#      gate: a 2-process `Snapshot.stream` whose rank 1 is SIGKILLed
#      mid-micro-commit must keep streaming via a degraded epoch
#      (fsck-clean chain, bit-exact restore), and a graceful
#      `leave()` + later re-join must re-plan the epoch world with
#      the joins/leaves recorded in the per-epoch chain metadata
#  12. mini-fleetsim smoke — 3 concurrent jobs (one SIGKILLed by a
#      rank-kill fault, one writing through a seeded outage window)
#      publishing into one shared TPUSNAP_FLEET_DIR; `tpusnap fleet
#      --check` must honor its full exit contract: 3 on the empty
#      fleet dir, 0 across the live fleet under generous thresholds,
#      2 against a seeded stale (non-final, old-commit) job record
#  13. CAS smoke — two sequential jobs take identical content through
#      one shared content-addressed store (TPUSNAP_CAS_DIR): the blobs
#      dedup to one job's worth, a gc sweep is SIGKILLed mid-delete by
#      a chaos plan on the store URL, the re-run gc steals the dead
#      sweeper's lease and converges, and `fsck --store` exits 0 with
#      the surviving job's refs intact
#  14. OPTIONAL real-backend cloud suite — when a `fake-gcs-server`
#      and/or `minio` binary is on PATH, run the `cloud_real` pytest
#      marker against the real server processes (skipped silently
#      when the binaries are absent)
#  15. tune smoke — `tpusnap tune` exit contract: 3 against an empty
#      history (insufficient comparable events), 0 with a plan against
#      a seeded history; then a TPUSNAP_AUTOTUNE=1 restore must stamp
#      the applied plan (`tuned: {plan_id, knobs}`) into its history
#      event; hermetic like the other smokes
#  16. access-ledger heatmap smoke — `tpusnap heatmap` exit contract:
#      3 with no reader ledgers, 0 after a partial read_object (with
#      coverage < 100% naming only the read leaf), and 2 under --check
#      when a 3-reader cohort's merged amplification crosses the
#      --max-amplification gate; hermetic like the other smokes
#
# Usage:
#   scripts/ci_gate.sh [SNAPSHOT_PATH]
#
#   SNAPSHOT_PATH        snapshot for step 4 (default: $TPUSNAP_CI_SNAPSHOT,
#                        else step 4 is skipped with a note)
#   TPUSNAP_CI_SKIP_TESTS=1   skip step 2 (cron boxes that only gate
#                             perf trends, not code)
#
# Exit: non-zero on the first failing gate, echoing which one.

set -u -o pipefail

cd "$(dirname "$0")/.."

fail() { echo "ci_gate: FAIL — $1" >&2; exit "$2"; }

# ---- 1. static analysis --------------------------------------------------
echo "ci_gate: [1/16] lint --check (AST invariants)"
env JAX_PLATFORMS=cpu python -m tpusnap lint --check
rc=$?
[ "$rc" -eq 0 ] || fail "tpusnap lint --check (rc=$rc)" "$rc"

# ---- 2. tier-1 -----------------------------------------------------------
if [ "${TPUSNAP_CI_SKIP_TESTS:-0}" != "1" ]; then
    echo "ci_gate: [2/16] tier-1 tests"
    rm -f /tmp/_t1.log
    # cloud_real excluded here: on a host with the server binaries the
    # real-backend suite belongs to step 8, not inside the fast tier.
    timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow and not cloud_real' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
    rc=${PIPESTATUS[0]}
    echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
    [ "$rc" -eq 0 ] || fail "tier-1 tests (rc=$rc)" "$rc"
else
    echo "ci_gate: [2/16] tier-1 tests skipped (TPUSNAP_CI_SKIP_TESTS=1)"
fi

# ---- 3. cross-run history gate ------------------------------------------
echo "ci_gate: [3/16] history --check (throughput + p99 write latency + restore read roofline)"
for kind in take bench; do
    python -m tpusnap history --check --kind "$kind" \
        --metric throughput_gbps --metric storage_write_p99_s --json
    rc=$?
    case "$rc" in
        0) echo "ci_gate: history[$kind] OK" ;;
        3) echo "ci_gate: history[$kind] insufficient comparable history (bootstrapping) — pass" ;;
        *) fail "history --check --kind $kind regressed (rc=$rc)" "$rc" ;;
    esac
done
# Restore lane: restore_roofline_fraction has no _s suffix, so the gate
# treats it higher-is-better — a read-path efficiency slide (fraction
# falling against its baseline) trips CI even when wall-clock hides it.
python -m tpusnap history --check --kind restore \
    --metric restore_roofline_fraction --metric storage_read_p99_s --json
rc=$?
case "$rc" in
    0) echo "ci_gate: history[restore] OK" ;;
    3) echo "ci_gate: history[restore] insufficient comparable history (bootstrapping) — pass" ;;
    *) fail "history --check --kind restore regressed (rc=$rc)" "$rc" ;;
esac

# ---- 4. analyze doctor on the latest snapshot ---------------------------
SNAP="${1:-${TPUSNAP_CI_SNAPSHOT:-}}"
if [ -n "$SNAP" ]; then
    echo "ci_gate: [4/16] analyze --check $SNAP"
    python -m tpusnap analyze --check --history --min-read-roofline 0.4 "$SNAP"
    rc=$?
    case "$rc" in
        0) echo "ci_gate: analyze OK" ;;
        3) echo "ci_gate: analyze found no telemetry in $SNAP — pass (knob-off take)" ;;
        *) fail "analyze --check $SNAP (rc=$rc)" "$rc" ;;
    esac
else
    echo "ci_gate: [4/16] analyze skipped (no snapshot; pass a path or set TPUSNAP_CI_SNAPSHOT)"
fi

# ---- 5. checkpoint-SLO gate smoke ---------------------------------------
echo "ci_gate: [5/16] slo --check smoke (exit contract: 0 healthy / 2 breach / 3 no records)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, shutil, subprocess, sys, tempfile, time

work = tempfile.mkdtemp(prefix="tpusnap_ci_slo_")
tele = os.path.join(work, "tele")
# Hermetic like the timeline smoke: the takes here must not feed the
# HOST history this gate's own step 3 grades.
env = dict(os.environ, JAX_PLATFORMS="cpu",
           TPUSNAP_TELEMETRY_DIR=tele, TPUSNAP_HISTORY="0")
import atexit
atexit.register(shutil.rmtree, work, True)

def slo(*extra, tdir=tele):
    e = dict(env, TPUSNAP_TELEMETRY_DIR=tdir)
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", "slo", "--check", *extra],
        capture_output=True, text=True, env=e, timeout=120,
    )

def die(msg):
    print(f"slo smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

# (a) empty telemetry dir -> exit 3
r = slo(tdir=os.path.join(work, "empty"))
if r.returncode != 3:
    die(f"empty dir: expected exit 3, got {r.returncode}: {r.stderr[-300:]}")

# (b) committed take -> healthy under a generous RPO threshold -> exit 0
take = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu');\n"
    "import jax; jax.config.update('jax_platforms','cpu');\n"
    "import numpy as np, sys\n"
    "from tpusnap import Snapshot, StateDict\n"
    "Snapshot.take(sys.argv[1], {'a': StateDict(w=np.arange(200000, dtype=np.float32))})\n"
)
subprocess.run([sys.executable, "-c", take, os.path.join(work, "snap")],
               check=True, env=env, timeout=180)
r = slo("--rpo", "3600")
if r.returncode != 0:
    die(f"healthy: expected exit 0, got {r.returncode}: {r.stdout[-300:]}{r.stderr[-300:]}")

# (c) seeded stale commit -> breach -> exit 2
rec_path = os.path.join(tele, "slo", "rank_0.json")
rec = json.load(open(rec_path))
rec["last_commit_ts"] = time.time() - 900  # 15 minutes stale
json.dump(rec, open(rec_path, "w"))
r = slo("--rpo", "60")
if r.returncode != 2:
    die(f"stale breach: expected exit 2, got {r.returncode}: {r.stdout[-300:]}")
print("slo smoke: OK (3/3 contract legs)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "slo --check smoke (rc=$rc)" "$rc"

# ---- 6. delta soak smoke -------------------------------------------------
echo "ci_gate: [6/16] delta soak smoke (stream ~30s: slo --check green, RPO <= 2x cadence; SIGKILL -> torn-tail contracts)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, re, shutil, signal, subprocess, sys, tempfile, time

work = tempfile.mkdtemp(prefix="tpusnap_ci_delta_")
tele = os.path.join(work, "tele")
# Hermetic observability (see the slo/timeline smokes) + the RPO
# objective ARMED for the whole soak: a healthy stream must never
# breach it, and `slo --check` reads the same env threshold.
env = dict(os.environ, JAX_PLATFORMS="cpu",
           TPUSNAP_TELEMETRY_DIR=tele, TPUSNAP_HISTORY="0",
           TPUSNAP_SLO_RPO_S="10",
           TPUSNAP_HEARTBEAT_INTERVAL_S="0.05")
import atexit
atexit.register(shutil.rmtree, work, True)

def die(msg):
    print(f"delta soak: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

CADENCE = 1.0
_SOAK = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from tpusnap import Snapshot, StateDict

root, duration, cadence, kill_mode = (
    sys.argv[1], float(sys.argv[2]), float(sys.argv[3]), sys.argv[4]
)
if kill_mode == "kill":
    # Make the torn window deterministic: the first payload write into
    # a delta member past seq 1 announces itself and lingers, so the
    # parent's SIGKILL always lands inside a micro-commit.
    import tpusnap.storage_plugins.fs as fs_mod
    orig_write = fs_mod.FSStoragePlugin.write
    fired = [False]
    async def hooked(self, write_io):
        root_s = getattr(self, "root", "")
        if (not fired[0] and "delta-0000" in root_s
                and not root_s.endswith("delta-000001")
                and not write_io.path.startswith(".tpusnap")):
            fired[0] = True
            print("MARK", flush=True)
            time.sleep(2.0)
        await orig_write(self, write_io)
    fs_mod.FSStoragePlugin.write = hooked

state = {"m": StateDict(w=np.zeros((512, 512), np.float32), step=0)}
stream = Snapshot.stream(root, state, cadence_s=cadence)
t0, k = time.monotonic(), 0
while time.monotonic() - t0 < duration:
    k += 1
    state["m"]["w"][k % 512, :] = float(k)
    state["m"]["step"] = k
    stream.mark_step(bytes_changed=2048)
    time.sleep(0.01)
stream.close()
stream.raise_if_failed()
print("STATS " + json.dumps(stream.stats), flush=True)
"""

# (a) healthy ~30 s soak: clean close, slo --check green, measured
# steady-state RPO (max micro-commit interval) <= 2x cadence.
root = os.path.join(work, "stream")
r = subprocess.run(
    [sys.executable, "-c", _SOAK, root, "30", str(CADENCE), "run"],
    capture_output=True, text=True, env=env, timeout=240,
)
if r.returncode != 0:
    die(f"soak child failed rc={r.returncode}: {r.stdout[-400:]}{r.stderr[-400:]}")
m = re.search(r"STATS (\{.*\})", r.stdout)
if not m:
    die(f"soak printed no stats: {r.stdout[-400:]}")
stats = json.loads(m.group(1))
if stats["commits"] < 3:
    die(f"soak produced only {stats['commits']} micro-commit(s)")
rpo = stats.get("max_commit_interval_s")
if rpo is None or rpo > 2 * CADENCE:
    die(f"measured RPO {rpo}s exceeds 2x cadence ({2 * CADENCE}s)")
r = subprocess.run(
    [sys.executable, "-m", "tpusnap", "slo", "--check"],
    capture_output=True, text=True, env=env, timeout=120,
)
if r.returncode != 0:
    die(f"slo --check after soak: expected 0, got {r.returncode}: "
        f"{r.stdout[-300:]}")
print(f"delta soak: healthy leg OK ({stats['commits']} commits, "
      f"max interval {rpo}s <= {2 * CADENCE}s, slo --check green)")

# (b) SIGKILL inside a micro-commit -> torn-tail exit contracts.
root2 = os.path.join(work, "stream_kill")
proc = subprocess.Popen(
    [sys.executable, "-c", _SOAK, root2, "60", "0.4", "kill"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    start_new_session=True,
)
buf, deadline = "", time.monotonic() + 120
while time.monotonic() < deadline and "MARK" not in buf:
    line = proc.stdout.readline()
    if line == "":
        break
    buf += line
if "MARK" not in buf:
    os.killpg(proc.pid, signal.SIGKILL); proc.wait(timeout=60)
    die(f"kill soak never reached the write window: {buf[-400:]}")
time.sleep(0.3)
os.killpg(proc.pid, signal.SIGKILL)
proc.wait(timeout=60)

def cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", *args],
        capture_output=True, text=True, env=env, timeout=120,
    )

torn = sorted(
    d for d in os.listdir(root2)
    if d.startswith("delta-")
    and not os.path.exists(os.path.join(root2, d, ".snapshot_metadata"))
)
if not torn:
    die(f"SIGKILL left no torn member under {root2}: {os.listdir(root2)}")
member = os.path.join(root2, torn[-1])
r = cli("fsck", member)
if r.returncode != 4:
    die(f"member fsck: expected 4 (torn), got {r.returncode}: {r.stdout[-300:]}")
if "torn delta micro-commit" not in r.stdout:
    die(f"member fsck does not name the torn delta state: {r.stdout[-300:]}")
r = cli("fsck", root2)
if r.returncode != 4:
    die(f"root fsck: expected 4 (torn tail), got {r.returncode}: {r.stdout[-300:]}")
r = cli("timeline", member)
if r.returncode not in (3, 4):
    die(f"timeline on torn member: expected 4 (or 3 pre-flush), got "
        f"{r.returncode}: {r.stderr[-300:]}")
print("delta soak: OK (healthy RPO leg + torn-tail contract leg)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "delta soak smoke (rc=$rc)" "$rc"

# ---- 7. flight-recorder timeline smoke ----------------------------------
echo "ci_gate: [7/16] timeline smoke (exit contract: 0 committed / 4 torn / 3 no data)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import os, shutil, signal, subprocess, sys, tempfile

work = tempfile.mkdtemp(prefix="tpusnap_ci_timeline_")
# Hermetic observability: the smoke's takes must not append kind=take
# events to the HOST history this gate's own step 3 grades, nor leak
# flight-copy dirs under the real telemetry dir — scope both to the
# workdir that is removed at exit.
env = dict(os.environ, JAX_PLATFORMS="cpu",
           TPUSNAP_TELEMETRY_DIR=os.path.join(work, "tele"),
           TPUSNAP_HISTORY="0")
# Cron boxes run this forever: the snapshots made here must not
# accumulate under /tmp.
import atexit
atexit.register(shutil.rmtree, work, True)

def timeline(path, *extra):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", "timeline", path, *extra],
        capture_output=True, text=True, env=env, timeout=180,
    )

def die(msg):
    print(f"timeline smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

# (a) no flight data -> exit 3
empty = os.path.join(work, "empty"); os.makedirs(empty)
r = timeline(empty)
if r.returncode != 3:
    die(f"empty dir: expected exit 3, got {r.returncode}: {r.stderr[-300:]}")

# (b) committed take -> exit 0
committed = os.path.join(work, "committed")
take = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu');\n"
    "import jax; jax.config.update('jax_platforms','cpu');\n"
    "import numpy as np, sys\n"
    "from tpusnap import Snapshot, StateDict\n"
    "Snapshot.take(sys.argv[1], {'a': StateDict(w=np.arange(200000, dtype=np.float32))})\n"
)
subprocess.run([sys.executable, "-c", take, committed], check=True, env=env, timeout=180)
r = timeline(committed)
if r.returncode != 0:
    die(f"committed: expected exit 0, got {r.returncode}: {r.stderr[-300:]}")

# (c) SIGKILL mid-take -> torn, post-mortem section, exit 4
torn = os.path.join(work, "torn")
kill = (
    "import os, sys; os.environ.setdefault('JAX_PLATFORMS','cpu');\n"
    "os.environ['TPUSNAP_DISABLE_BATCHING']='1';\n"
    "os.environ['TPUSNAP_HEARTBEAT_INTERVAL_S']='0.05';\n"
    "os.environ['TPUSNAP_FAULT_SPEC']='latency_ms=300,crash_after_op=write:4';\n"
    "import jax; jax.config.update('jax_platforms','cpu');\n"
    "import numpy as np\n"
    "from tpusnap import Snapshot, StateDict\n"
    "state={f'w{i}': np.random.default_rng(i).standard_normal((128,128)).astype(np.float32) for i in range(8)}\n"
    "Snapshot.take('chaos+fs://'+sys.argv[1], {'a': StateDict(**state)})\n"
)
r = subprocess.run([sys.executable, "-c", kill, torn], capture_output=True, text=True, env=env, timeout=180)
if r.returncode != -signal.SIGKILL:
    die(f"kill child: expected SIGKILL, got {r.returncode}: {r.stdout[-300:]}")
r = timeline(torn)
if r.returncode != 4:
    die(f"torn: expected exit 4, got {r.returncode}: {r.stderr[-300:]}")
if "POST-MORTEM" not in r.stdout:
    die("torn: post-mortem section missing from output")
print("timeline smoke: OK (3/3 contract legs)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "timeline smoke (rc=$rc)" "$rc"

# ---- 8. write-back tiering smoke ----------------------------------------
echo "ci_gate: [8/16] tiering smoke (local commit -> SIGKILL mid-drain -> resumed drain -> remote-durable)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, shutil, signal, subprocess, sys, tempfile

work = tempfile.mkdtemp(prefix="tpusnap_ci_tier_")
# Hermetic observability: tier status + history scoped to the workdir.
env = dict(os.environ, JAX_PLATFORMS="cpu",
           TPUSNAP_TELEMETRY_DIR=os.path.join(work, "tele"),
           TPUSNAP_HISTORY="0", TPUSNAP_TIER_DRAIN="0")
import atexit
atexit.register(shutil.rmtree, work, True)

def die(msg):
    print(f"tiering smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

def cli(*args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", *args],
        capture_output=True, text=True, env=dict(env, **kw), timeout=180,
    )

cache = os.path.join(work, "cache")
remote = os.path.join(work, "remote")
url = f"tier+local={cache}+remote=fs://{remote}/snap"
local_dir = os.path.join(cache, remote.lstrip("/"), "snap")

# (a) tiered take (chaos-wrapped remote scheme would not matter here:
# the take never touches the remote) -> fsck committed + local-committed,
# drain --status exit 2 (tiered, not yet durable).
take = (
    "import os, sys; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "os.environ['TPUSNAP_DISABLE_BATCHING']='1'\n"
    "import jax; jax.config.update('jax_platforms','cpu')\n"
    "import numpy as np\n"
    "from tpusnap import Snapshot, StateDict\n"
    "state={f'w{i}': np.random.default_rng(i).standard_normal((128,128)).astype(np.float32) for i in range(6)}\n"
    "Snapshot.take(sys.argv[1], {'a': StateDict(**state)})\n"
)
subprocess.run([sys.executable, "-c", take, url], check=True, env=env, timeout=180)
r = cli("fsck", local_dir)
if r.returncode != 0 or "local-committed" not in r.stdout:
    die(f"post-take fsck: rc={r.returncode}: {r.stdout[-300:]}")
r = cli("drain", local_dir, "--status")
if r.returncode != 2:
    die(f"drain --status pre-drain: expected 2, got {r.returncode}")

# (b) kill the uploader mid-drain (chaos remote SIGKILLs after the 3rd
# successful upload), then the resumed drain must reach remote-durable
# re-uploading nothing already journal-proven.
kill_drain = (
    "import os, sys; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "os.environ['TPUSNAP_FAULT_SPEC']='crash_after_op=write:3'\n"
    "import jax; jax.config.update('jax_platforms','cpu')\n"
    "from tpusnap import tiering\n"
    "spec = tiering.parse_tier_url(sys.argv[1])\n"
    "tiering.drain_snapshot(sys.argv[1], remote_url='chaos+'+spec.remote_url)\n"
)
r = subprocess.run([sys.executable, "-c", kill_drain, url],
                   capture_output=True, text=True, env=env, timeout=180)
if r.returncode != -signal.SIGKILL:
    die(f"kill drain: expected SIGKILL, got {r.returncode}: {r.stdout[-300:]}{r.stderr[-300:]}")
r = cli("fsck", local_dir)
if r.returncode != 0 or "local-committed" not in r.stdout:
    die(f"post-kill fsck must stay local-committed: {r.stdout[-300:]}")

r = cli("drain", url, "--json")
if r.returncode != 0:
    die(f"resumed drain: expected 0, got {r.returncode}: {r.stdout[-300:]}{r.stderr[-300:]}")
rep = json.loads(r.stdout)
if rep["state"] != "durable" or rep["blobs_skipped"] < 2:
    die(f"resumed drain did not skip journal-proven blobs: {rep}")

# (c) exit contracts at the durable state + the remote restores.
r = cli("fsck", local_dir)
if r.returncode != 0 or "remote-durable" not in r.stdout:
    die(f"post-drain fsck: {r.stdout[-300:]}")
r = cli("drain", local_dir, "--status")
if r.returncode != 0:
    die(f"drain --status post-drain: expected 0, got {r.returncode}")
r = cli("fsck", os.path.join(remote, "snap"))
if r.returncode != 0:
    die(f"remote fsck: expected 0 (committed), got {r.returncode}: {r.stdout[-300:]}")
print(f"tiering smoke: OK (take local, SIGKILL mid-drain, resume skipped "
      f"{rep['blobs_skipped']}/{rep['blobs_skipped']+rep['blobs_uploaded']} blobs, remote-durable)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "tiering smoke (rc=$rc)" "$rc"

# ---- 9. fused-compression smoke ------------------------------------------
echo "ci_gate: [9/16] compression smoke (compressed take -> fsck/scrub clean -> bit-exact restore; auto bypasses locally, compresses on a throttled pipe)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import os, shutil, sys, tempfile

work = tempfile.mkdtemp(prefix="tpusnap_ci_compress_")
# Hermetic observability, same contract as the slo/timeline/tiering
# smokes: nothing here feeds the HOST history step 3 grades.
os.environ.update(JAX_PLATFORMS="cpu",
                  TPUSNAP_TELEMETRY_DIR=os.path.join(work, "tele"),
                  TPUSNAP_HISTORY="0")
import atexit
atexit.register(shutil.rmtree, work, True)

import numpy as np

from tpusnap import Snapshot, StateDict, compress, verify_snapshot
from tpusnap.knobs import override_compress


def die(msg):
    print(f"compression smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)


if not __import__("tpusnap")._native.compression_available():
    print("compression smoke: SKIP (native codec unavailable)")
    sys.exit(0)

# bf16-precision f32 (mantissa-truncated random): the shape the shuffle
# filter targets, with real entropy in the exponent planes.
rng = np.random.default_rng(0xC0)
a = rng.standard_normal((96 << 20) // 4).astype(np.float32)
a = (a.view(np.uint32) & np.uint32(0xFFFF0000)).view(np.float32)

# (a) forced-compressed take -> codec recorded, stored < logical,
# scrub clean, bit-exact restore.
on_path = os.path.join(work, "on", "snap")
with override_compress(mode="on", min_blob_bytes=1 << 20):
    Snapshot.take(on_path, {"app": StateDict(w=a)})
entry = Snapshot(on_path).metadata.manifest["0/app/w"]
if not entry.codec:
    die("forced take recorded no codec on the manifest entry")
stored = sum(
    os.path.getsize(os.path.join(r, f))
    for r, _, fs in os.walk(on_path)
    for f in fs
    if not f.endswith(".snapshot_metadata")
)
if stored >= a.nbytes:
    die(f"compressed take stored {stored} >= logical {a.nbytes}")
rep = verify_snapshot(on_path)
if not rep.clean or rep.corrupt:
    die(f"scrub of compressed snapshot not clean: {rep}")
tgt = {"app": StateDict(w=np.zeros_like(a))}
Snapshot(on_path).restore(tgt)
if not np.array_equal(tgt["app"]["w"], a):
    die("compressed restore is not bit-exact")

# (b) auto policy against a PINNED fast pipe: seed the ceiling
# registry with a known-fast sample for this backend label, so the
# gate asserts the policy's decision logic, not this runner's disk
# weather (a cgroup-throttled CI disk measuring under codec/1.3
# would legitimately compress — bench.py owns the measured-local
# claim). Manifest stays codec-free on a bypassed take.
from tpusnap.storage_plugin import url_to_storage_plugin

compress._reset_ceilings()
auto_path = os.path.join(work, "auto", "snap")
_probe_plugin = url_to_storage_plugin(auto_path)
compress.note_pipe_ceiling(compress.pipe_ceiling_key(_probe_plugin), 100.0)
with override_compress(mode="auto"):
    Snapshot.take(auto_path, {"app": StateDict(w=a)})
dec = compress.LAST_DECISION
if dec is None or dec.compress:
    die(f"auto against a pinned-fast pipe must bypass, got {dec}")
if dec.reason != "pipe_outruns_codec":
    die(f"auto bypass drew the wrong reason: {dec}")
if Snapshot(auto_path).metadata.manifest["0/app/w"].codec:
    die("auto-bypassed take recorded a codec")

# (c) auto policy against a bandwidth-throttled pipe (chaos token
# bucket at 0.05 GB/s, far under this host's measured codec rate):
# must compress, and the throttled snapshot still restores bit-exact.
compress._reset_ceilings()
thr_path = os.path.join(work, "thr", "snap")
with override_compress(mode="auto"):
    Snapshot.take(
        f"chaos+file://{thr_path}",
        {"app": StateDict(w=a)},
        storage_options={
            "fault_plan": "transient_per_op=0,bandwidth_gbps=0.05"
        },
    )
dec = compress.LAST_DECISION
if dec is None or not dec.compress:
    die(f"auto on a 0.05 GB/s pipe must compress, got {dec}")
tgt = {"app": StateDict(w=np.zeros_like(a))}
Snapshot(thr_path).restore(tgt)
if not np.array_equal(tgt["app"]["w"], a):
    die("throttled compressed restore is not bit-exact")

print(
    "compression smoke: OK (forced take scrub-clean + bit-exact, "
    f"ratio {a.nbytes / stored:.2f}x; auto bypassed the pinned-fast "
    f"pipe and compressed on the throttled one)"
)
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "compression smoke (rc=$rc)" "$rc"

# ---- 10. rank-failure smoke ----------------------------------------------
echo "ci_gate: [10/16] rank-failure smoke (chaos rank-kill -> fast RankFailedError; degrade-mode replicated take -> committed + scrub clean)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import atexit, os, re, shutil, subprocess, sys, tempfile

work = tempfile.mkdtemp(prefix="tpusnap_ci_rankfail_")
atexit.register(shutil.rmtree, work, True)

def die(msg):
    print(f"rank-failure smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

# The world script re-imported by run_subprocess_world's rank children
# must live in an importable file (a heredoc has no module path).
WORLD = r'''
import os, signal, sys, time

import numpy as np


def _arrays(seed=5, n=4):
    rng = np.random.default_rng(seed)
    return {
        f"w{i}": rng.standard_normal(16384).astype(np.float32)
        for i in range(n)
    }


def world_fast_abort(snap_dir):
    # Leg (a): TPUSNAP_FAULT_SPEC="rank=1,...,crash_after_op=write:1"
    # SIGKILLs exactly rank 1 after its first chaos blob write; rank 0
    # must fail fast with RankFailedError naming it — seconds, not the
    # 600 s barrier timeout.
    from tpusnap import RankFailedError, Snapshot, StateDict

    state = {"m": StateDict(**_arrays())}
    t0 = time.monotonic()
    try:
        Snapshot.take("chaos+fs://" + snap_dir, state, replicated=["**"])
    except RankFailedError as e:
        dt = time.monotonic() - t0
        assert e.ranks == [1], e.ranks
        assert dt <= 15.0, f"detection took {dt:.1f}s"
        print(f"RANKFAILED dt={dt:.2f}", flush=True)
        os._exit(0)  # skip the shutdown rendezvous with the dead peer
    raise AssertionError("rank 0 never observed the rank failure")


def world_degraded(snap_dir):
    # Leg (b): TPUSNAP_RANK_FAILURE=degrade + a fully-replicated state:
    # rank 1 dies mid-write, rank 0 completes the take, scrubs it
    # clean, and the metadata records the adoption.
    from tpusnap import Snapshot, StateDict, verify_snapshot
    from tpusnap.comm import get_communicator

    comm = get_communicator()
    arrays = _arrays(seed=9)
    if comm.rank == 1:
        import tpusnap.storage_plugins.fs as fs_mod

        orig = fs_mod.FSStoragePlugin.write
        fired = [0]

        async def hooked(self, write_io):
            await orig(self, write_io)
            if not write_io.path.startswith(".tpusnap"):
                fired[0] += 1
                if fired[0] == 1:
                    os.kill(os.getpid(), signal.SIGKILL)

        fs_mod.FSStoragePlugin.write = hooked
    snap = Snapshot.take(snap_dir, {"m": StateDict(**arrays)}, replicated=["**"])
    deg = (snap.metadata.extras or {}).get("degraded")
    assert deg and deg["dead_ranks"] == [1], deg
    rep = verify_snapshot(snap_dir)
    assert rep.clean and not rep.corrupt, rep
    tgt = {"m": StateDict(**{k: np.zeros_like(v) for k, v in arrays.items()})}
    Snapshot(snap_dir).restore(tgt)
    for k, v in arrays.items():
        assert np.array_equal(tgt["m"][k], v), k
    print("DEGRADED-COMMITTED", flush=True)
    os._exit(0)  # skip the shutdown rendezvous with the dead peer


if __name__ == "__main__":
    from tpusnap.test_utils import run_subprocess_world

    mode, snap = sys.argv[1], sys.argv[2]
    env = {
        "TPUSNAP_LIVENESS_TTL_S": "2.0",
        "TPUSNAP_HEARTBEAT_INTERVAL_S": "0.1",
        "TPUSNAP_DISABLE_BATCHING": "1",
        "TPUSNAP_HISTORY": "0",
        "TPUSNAP_TELEMETRY_DIR": os.path.join(os.path.dirname(snap), "tele"),
    }
    if mode == "abort":
        env["TPUSNAP_FAULT_SPEC"] = (
            "rank=1,transient_per_op=0,crash_after_op=write:1"
        )
    else:
        env["TPUSNAP_RANK_FAILURE"] = "degrade"
    fn = world_fast_abort if mode == "abort" else world_degraded
    try:
        run_subprocess_world(fn, world_size=2, args=[snap], extra_env=env,
                             timeout=120)
    except RuntimeError as e:
        # Rank 1 died by design; rank 0's printed proof rides the logs.
        print(str(e)[-4000:])
'''
world_py = os.path.join(work, "ci_rankfail_world.py")
with open(world_py, "w") as f:
    f.write(WORLD)

# `python world.py` puts the script's own dir (not the repo root this
# gate cd'd into) at sys.path[0] — hand the coordinator the package
# explicitly; the rank children get it from run_subprocess_world.
env = dict(os.environ, JAX_PLATFORMS="cpu",
           PYTHONPATH=os.getcwd(),
           TPUSNAP_TELEMETRY_DIR=os.path.join(work, "tele"),
           TPUSNAP_HISTORY="0")

# (a) fast-abort exit contract.
r = subprocess.run(
    [sys.executable, world_py, "abort", os.path.join(work, "snap_abort")],
    capture_output=True, text=True, env=env, timeout=300,
)
m = re.search(r"RANKFAILED dt=([0-9.]+)", r.stdout)
if r.returncode != 0 or not m:
    die(f"fast-abort leg rc={r.returncode}: {r.stdout[-1200:]}{r.stderr[-600:]}")
dt = float(m.group(1))

# (b) degrade-mode replicated take commits + scrubs clean.
r = subprocess.run(
    [sys.executable, world_py, "degrade", os.path.join(work, "snap_degrade")],
    capture_output=True, text=True, env=env, timeout=300,
)
if r.returncode != 0 or "DEGRADED-COMMITTED" not in r.stdout:
    die(f"degrade leg rc={r.returncode}: {r.stdout[-1200:]}{r.stderr[-600:]}")

print(f"rank-failure smoke: OK (survivor detected the SIGKILLed rank in "
      f"{dt:.1f}s; degraded replicated take committed, scrubbed clean, "
      "restored bit-exact)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "rank-failure smoke (rc=$rc)" "$rc"

# ---- 11. elastic-stream smoke ---------------------------------------------
echo "ci_gate: [11/16] elastic-stream smoke (2-process stream survives a SIGKILLed rank via a degraded epoch; graceful leave + re-join re-plan the world)"
env JAX_PLATFORMS=cpu TPUSNAP_HISTORY=0 python -m pytest -q \
    tests/test_stream_elastic.py::test_stream_survives_rank_sigkill \
    tests/test_stream_elastic.py::test_stream_graceful_leave_and_rejoin \
    -p no:cacheprovider -p no:xdist -p no:randomly
rc=$?
[ "$rc" -eq 0 ] || fail "elastic-stream smoke (rc=$rc)" "$rc"

# ---- 12. fleet observability smoke ----------------------------------------
echo "ci_gate: [12/16] mini-fleetsim smoke (3 jobs, rank-kill + outage faults; fleet --check exit contract: 0 healthy / 2 breach / 3 no records)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import atexit, json, os, shutil, signal, subprocess, sys, tempfile, time

work = tempfile.mkdtemp(prefix="tpusnap_ci_fleet_")
atexit.register(shutil.rmtree, work, True)
fleet_dir = os.path.join(work, "fleet")

def die(msg):
    print(f"mini-fleetsim: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

def fleet(*extra, check=True):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", "fleet", "--dir", fleet_dir,
         *(["--check"] if check else []), *extra],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120,
    )

# (a) empty fleet dir -> exit 3 (no verdict without records).
os.makedirs(fleet_dir)
r = fleet()
if r.returncode != 3:
    die(f"empty dir: expected exit 3, got {r.returncode}: {r.stdout[-300:]}")

# (b) 3 concurrent jobs against one shared fleet dir: a healthy
# trainer, one writing through a seeded 2 s outage window, and one
# SIGKILLed by a chaos rank-kill after its first blob write. Hermetic:
# per-job telemetry dirs under the workdir, HOST history untouched.
_JOB = (
    "import os, sys; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import jax; jax.config.update('jax_platforms','cpu')\n"
    "import numpy as np\n"
    "from tpusnap import Snapshot, StateDict\n"
    "state={'m': StateDict(w=np.arange(1<<18, dtype=np.float32))}\n"
    "for k in range(2):\n"
    "    Snapshot.take(f'chaos+fs://{sys.argv[1]}/t{k}', state)\n"
)
jobs = []
for name, fault in (
    ("mini-ok", None),
    ("mini-outage", "seed=1,transient_per_op=0,outage=write:0:2"),
    # latency_ms keeps the doomed job alive across a few 50 ms
    # heartbeat ticks so its fleet record exists before the SIGKILL.
    ("mini-killed", "seed=2,transient_per_op=0,latency_ms=300,"
                    "crash_after_op=write:2"),
):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        TPUSNAP_FLEET_DIR=fleet_dir, TPUSNAP_JOB_ID=name,
        TPUSNAP_TELEMETRY_DIR=os.path.join(work, "tele", name),
        TPUSNAP_HISTORY="0", TPUSNAP_HEARTBEAT_INTERVAL_S="0.05",
        TPUSNAP_DISABLE_BATCHING="1",
    )
    if fault:
        env["TPUSNAP_FAULT_SPEC"] = fault
    jobs.append((name, subprocess.Popen(
        [sys.executable, "-c", _JOB, os.path.join(work, "dest", name)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )))
rcs = {}
for name, p in jobs:
    out, _ = p.communicate(timeout=180)
    rcs[name] = p.returncode
    if name == "mini-killed":
        if p.returncode != -signal.SIGKILL:
            die(f"{name}: expected SIGKILL, got {p.returncode}: {out[-400:]}")
    elif p.returncode != 0:
        die(f"{name}: rc={p.returncode}: {out[-400:]}")

# All three jobs left a record (the killed one non-final) -> healthy
# under generous thresholds -> exit 0.
r = fleet("--rpo", "3600", "--lag-s", "3600", "--json")
if r.returncode != 0:
    die(f"healthy leg: expected exit 0, got {r.returncode}: {r.stdout[-400:]}")
doc = json.loads(r.stdout)
if doc["rollup"]["n_jobs"] < 3:
    die(f"expected >=3 job records, folded {doc['rollup']['n_jobs']}")
killed = [j for j in doc["rollup"]["jobs"] if j["job_id"] == "mini-killed"]
if not killed or killed[0]["final"]:
    die(f"SIGKILLed job must leave a NON-final record: {killed}")

# (c) seeded stale job (non-final record, 15-minute-old commit) + a
# tight --rpo -> breach -> exit 2.
now = time.time()
stale = {
    "v": 1, "job_id": "mini-stale", "pid": 1, "ts": now - 850,
    "rank": 0, "world_size": 1, "state": "running",
    "slo": {"last_commit_ts": now - 900, "started_ts": now - 900,
            "data_at_risk_bytes": 1 << 20},
}
with open(os.path.join(fleet_dir, "mini-stale.json"), "w") as f:
    json.dump(stale, f)
r = fleet("--rpo", "60")
if r.returncode != 2:
    die(f"stale breach: expected exit 2, got {r.returncode}: {r.stdout[-400:]}")
if "mini-stale" not in r.stdout:
    die(f"breach verdict does not name the stale job: {r.stdout[-400:]}")
print("mini-fleetsim: OK (3/3 contract legs across a 3-job fleet)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "mini-fleetsim smoke (rc=$rc)" "$rc"

# ---- 13. content-addressed store smoke ------------------------------------
echo "ci_gate: [13/16] CAS smoke (two jobs share a base through one store; SIGKILL mid-gc-sweep -> re-run gc converges -> fsck --store exit 0)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import atexit, os, shutil, signal, subprocess, sys, tempfile, time

work = tempfile.mkdtemp(prefix="tpusnap_ci_cas_")
atexit.register(shutil.rmtree, work, True)
store = os.path.join(work, "store")

def die(msg):
    print(f"cas-smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

def run(cmd, env=None, timeout=120):
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        env=env or dict(os.environ, JAX_PLATFORMS="cpu"),
    )

def cli(*args, env=None):
    return run([sys.executable, "-m", "tpusnap", *args], env=env)

# (a) two sequential jobs take the SAME content through one shared
# store: the second job's payload must dedup to refs (blob count stays
# at one job's worth), both commit, both fsck clean.
_JOB = (
    "import os, sys; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import jax; jax.config.update('jax_platforms','cpu')\n"
    "import numpy as np\n"
    "from tpusnap import Snapshot, StateDict\n"
    "rng = np.random.default_rng(7)\n"
    "state = {'m': StateDict(**{f'w{i}': rng.standard_normal((128, 128))"
    ".astype(np.float32) for i in range(4)})}\n"
    "Snapshot.take(sys.argv[1], state)\n"
)
env = dict(
    os.environ, JAX_PLATFORMS="cpu", TPUSNAP_CAS_DIR=store,
    TPUSNAP_DISABLE_BATCHING="1", TPUSNAP_HISTORY="0",
    TPUSNAP_TELEMETRY_DIR=os.path.join(work, "tele"),
)
for job in ("jobA", "jobB"):
    r = run([sys.executable, "-c", _JOB, os.path.join(work, job)], env=env)
    if r.returncode != 0:
        die(f"{job} take failed: {r.stderr[-400:]}")
blobs_dir = os.path.join(store, "blobs")
n_blobs = len(os.listdir(blobs_dir))
if n_blobs != 4:
    die(f"expected 4 deduped blobs for 2 jobs x 4 tensors, got {n_blobs}")
r = cli("fsck", "--store", store)
if r.returncode != 0:
    die(f"fsck --store after 2 jobs: expected exit 0, got {r.returncode}: "
        f"{r.stdout[-300:]}{r.stderr[-300:]}")

# (b) job A retires: its dir goes away, its root record and the now
# half-orphaned blobs age past the grace window (backdated mtimes).
shutil.rmtree(os.path.join(work, "jobA"))
old = time.time() - 3600
for sub in ("roots", "blobs"):
    d = os.path.join(store, sub)
    for name in os.listdir(d):
        os.utime(os.path.join(d, name), (old, old))

# (c) SIGKILL mid-gc-sweep: a chaos-wrapped store URL kills the sweeper
# right after its first delete. Its lease is taken with a 1 s TTL so
# the re-run can steal it.
chaos_env = dict(
    env, TPUSNAP_FAULT_SPEC="crash_after_op=delete:1",
    TPUSNAP_CAS_LEASE_TTL_S="1",
)
r = cli("gc", "--store", f"chaos+fs://{store}", "--force", env=chaos_env)
if r.returncode != -signal.SIGKILL:
    die(f"chaos gc: expected SIGKILL, got {r.returncode}: {r.stderr[-400:]}")
time.sleep(1.2)  # let the dead sweeper's lease expire

# (d) re-run gc converges: job A's stale root sweeps, job B's refs keep
# every blob, and the store fscks clean with zero dangling refs.
r = cli("gc", "--store", store, "--force", env=env)
if r.returncode != 0:
    die(f"gc re-run: expected exit 0, got {r.returncode}: {r.stderr[-400:]}")
r = cli("fsck", "--store", store)
if r.returncode != 0:
    die(f"fsck --store after gc: expected exit 0, got {r.returncode}: "
        f"{r.stdout[-300:]}{r.stderr[-300:]}")
if len(os.listdir(blobs_dir)) != 4:
    die(f"job B's refs must keep all 4 blobs, got {len(os.listdir(blobs_dir))}")
r = cli("fsck", os.path.join(work, "jobB"), env=env)
if r.returncode != 0:
    die(f"job B fsck: expected exit 0, got {r.returncode}: {r.stdout[-300:]}")
print("cas-smoke: OK (dedup 2 jobs -> 4 blobs; mid-sweep SIGKILL -> "
      "converged gc -> clean fsck)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "CAS smoke (rc=$rc)" "$rc"

# ---- 14. optional real-backend cloud suite -------------------------------
if command -v fake-gcs-server >/dev/null 2>&1 || command -v minio >/dev/null 2>&1; then
    echo "ci_gate: [14/16] real-backend cloud suite (fake-gcs-server/minio found on PATH)"
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m cloud_real \
        -p no:cacheprovider -p no:xdist -p no:randomly
    rc=$?
    # pytest exit 5 = no tests collected/all skipped (e.g. only one
    # binary present and its client package missing) - not a failure.
    if [ "$rc" -ne 0 ] && [ "$rc" -ne 5 ]; then
        fail "real-backend cloud suite (rc=$rc)" "$rc"
    fi
else
    echo "ci_gate: [14/16] real-backend cloud suite skipped (no fake-gcs-server/minio on PATH)"
fi

# ---- 15. tune smoke ------------------------------------------------------
echo "ci_gate: [15/16] tune smoke (exit contract: 0 plan / 3 insufficient history; TPUSNAP_AUTOTUNE=1 restore stamps the applied plan)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, shutil, subprocess, sys, tempfile

work = tempfile.mkdtemp(prefix="tpusnap_ci_tune_")
tele = os.path.join(work, "tele")
# Hermetic: history lives in the tempdir, never the host's.
env = dict(os.environ, JAX_PLATFORMS="cpu", TPUSNAP_TELEMETRY_DIR=tele)
import atexit
atexit.register(shutil.rmtree, work, True)

def tune(*extra, e=None):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", "tune", "--check", *extra],
        capture_output=True, text=True, env=e or env, timeout=120,
    )

def die(msg):
    print(f"tune smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

# (a) empty history -> insufficient comparable events -> exit 3
r = tune(e=dict(env, TPUSNAP_TELEMETRY_DIR=os.path.join(work, "empty")))
if r.returncode != 3:
    die(f"empty history: expected exit 3, got {r.returncode}: "
        f"{r.stdout[-300:]}{r.stderr[-300:]}")

# (b) one real take+restore seeds a genuine restore event (correct
# plugin label), then clones of it give the cell enough evidence; the
# 1 GiB payload makes the probe-cadence rule fire deterministically
# against the 2 GiB default interval.
script = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import numpy as np, sys\n"
    "from tpusnap import Snapshot, StateDict\n"
    "s = {'a': StateDict(w=np.arange(200000, dtype=np.float32))}\n"
    "Snapshot.take(sys.argv[1], s)\n"
    "t = {'a': StateDict(w=np.zeros(200000, dtype=np.float32))}\n"
    "Snapshot(sys.argv[1]).restore(t)\n"
)
snap = os.path.join(work, "snap")
subprocess.run([sys.executable, "-c", script, snap],
               check=True, env=env, timeout=180)
hist = os.path.join(tele, "history.jsonl")
events = [json.loads(ln) for ln in open(hist) if ln.strip()]
base = next(e for e in reversed(events) if e.get("kind") == "restore")
with open(hist, "a") as f:
    for _ in range(3):
        seed = dict(base, bytes=1 << 30, wall_s=2.0)
        f.write(json.dumps(seed) + "\n")
r = tune("--kind", "restore")
if r.returncode != 0:
    die(f"seeded history: expected exit 0, got {r.returncode}: "
        f"{r.stdout[-400:]}{r.stderr[-300:]}")
r = tune("--kind", "restore", "--json")
plan = json.loads(r.stdout)
if not plan.get("ok") or not plan.get("plan_id") or not plan.get("knobs"):
    die(f"seeded plan must carry plan_id + knobs: {r.stdout[-400:]}")

# (c) TPUSNAP_AUTOTUNE=1 restore applies the plan and stamps
# `tuned: {plan_id, knobs}` into its history event.
restore = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import numpy as np, sys\n"
    "from tpusnap import Snapshot, StateDict\n"
    "t = {'a': StateDict(w=np.zeros(200000, dtype=np.float32))}\n"
    "Snapshot(sys.argv[1]).restore(t)\n"
)
subprocess.run([sys.executable, "-c", restore, snap], check=True,
               env=dict(env, TPUSNAP_AUTOTUNE="1"), timeout=180)
events = [json.loads(ln) for ln in open(hist) if ln.strip()]
last = next(e for e in reversed(events) if e.get("kind") == "restore")
tuned = last.get("tuned")
if not isinstance(tuned, dict) or not tuned.get("plan_id") or not tuned.get("knobs"):
    die(f"autotuned restore event must stamp tuned: {json.dumps(last)[:400]}")
if tuned["plan_id"] != plan["plan_id"]:
    die(f"applied plan_id {tuned['plan_id']} != planned {plan['plan_id']}")
print("tune smoke: OK (exit 3 empty, exit 0 seeded, autotune stamped "
      f"plan {tuned['plan_id']})")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "tune smoke (rc=$rc)" "$rc"

# ---- 16. access-ledger heatmap smoke ------------------------------------
echo "ci_gate: [16/16] heatmap smoke (exit contract: 3 no ledgers / 0 partial read_object coverage / 2 amplification breach)"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, shutil, subprocess, sys, tempfile

work = tempfile.mkdtemp(prefix="tpusnap_ci_heatmap_")
tele = os.path.join(work, "tele")
snap = os.path.join(work, "snap")
# Hermetic: ledgers land in the tempdir, never the host's telemetry.
env = dict(os.environ, JAX_PLATFORMS="cpu", TPUSNAP_TELEMETRY="1",
           TPUSNAP_TELEMETRY_DIR=tele)
import atexit
atexit.register(shutil.rmtree, work, True)

def heatmap(*extra, e=None):
    return subprocess.run(
        [sys.executable, "-m", "tpusnap", "heatmap", snap, *extra],
        capture_output=True, text=True, env=e or env, timeout=120,
    )

def die(msg):
    print(f"heatmap smoke: FAIL - {msg}", file=sys.stderr)
    sys.exit(1)

# (a) A snapshot nobody read: no ledgers -> exit 3.
take = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import numpy as np, sys\n"
    "from tpusnap import Snapshot, StateDict\n"
    "s = {'m': StateDict(**{f'w{i}': np.arange(4096 + i, dtype=np.float32)\n"
    "                       for i in range(8)})}\n"
    "Snapshot.take(sys.argv[1], s)\n"
)
subprocess.run([sys.executable, "-c", take, snap], check=True, env=env,
               timeout=180)
r = heatmap("--check")
if r.returncode != 3:
    die(f"no ledgers: expected exit 3, got {r.returncode}: "
        f"{r.stdout[-300:]}{r.stderr[-300:]}")

# (b) One partial reader (read_object of ONE of 8 leaves): coverage
# must fall below 100% and the read leaf must be the only one with
# bytes attributed.
read_one = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import sys\n"
    "from tpusnap import Snapshot\n"
    "Snapshot(sys.argv[1]).read_object('0/m/w3')\n"
)
subprocess.run([sys.executable, "-c", read_one, snap], check=True,
               env=env, timeout=180)
r = heatmap("--json")
if r.returncode != 0:
    die(f"partial reader: expected exit 0, got {r.returncode}: "
        f"{r.stderr[-300:]}")
doc = json.loads(r.stdout)
if not (0 < doc["coverage"] < 1.0):
    die(f"partial reader: coverage must be in (0,1), got {doc['coverage']}")
touched = [l["path"] for l in doc["leaves"] if l["bytes_read"]]
if touched != ["m/w3"]:
    die(f"partial reader: only m/w3 may carry bytes, got {touched}")
partial_cov = doc["coverage"]

# (c) A 3-reader full-restore cohort: merged amplification ~3x must
# trip a 2.5x --max-amplification gate (exit 2) and pass a 4x one.
restore = (
    "import os; os.environ.setdefault('JAX_PLATFORMS','cpu')\n"
    "import numpy as np, sys\n"
    "from tpusnap import Snapshot, StateDict\n"
    "t = {'m': StateDict(**{f'w{i}': np.zeros(4096 + i, dtype=np.float32)\n"
    "                       for i in range(8)})}\n"
    "Snapshot(sys.argv[1]).restore(t)\n"
)
for k in range(3):
    subprocess.run([sys.executable, "-c", restore, snap], check=True,
                   env=dict(env, TPUSNAP_JOB_ID=f"ci-reader-{k}"),
                   timeout=180)
r = heatmap("--json", "--check", "--max-amplification", "2.5")
if r.returncode != 2:
    die(f"cohort: expected breach exit 2, got {r.returncode}: "
        f"{r.stdout[-300:]}{r.stderr[-300:]}")
doc = json.loads(r.stdout)
if doc["n_readers"] < 4:  # 3 named readers + the read_object job
    die(f"cohort: expected >=4 distinct readers, got {doc['n_readers']}")
if not (doc["coverage"] > 0.99 and doc["amplification"] > 2.5):
    die(f"cohort: coverage {doc['coverage']} / amplification "
        f"{doc['amplification']} out of contract")
r = heatmap("--check", "--max-amplification", "4")
if r.returncode != 0:
    die(f"cohort under a 4x budget: expected exit 0, got {r.returncode}")
print("heatmap smoke: OK (exit 3 no ledgers, partial coverage "
      f"{partial_cov:.2f} -> only m/w3, cohort amplification "
      f"{doc['amplification']:.2f}x gated)")
PYEOF
rc=$?
[ "$rc" -eq 0 ] || fail "heatmap smoke (rc=$rc)" "$rc"

echo "ci_gate: PASS"
