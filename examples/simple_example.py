"""Canonical tpusnap usage: an epoch loop with resumable app state.

Mirrors /root/reference/examples/simple_example.py:50-82 — train a tiny
model, snapshot every epoch, kill/resume from the latest snapshot.

Run: python examples/simple_example.py [--resume-from PATH]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpusnap.test_utils import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even under a sitecustomize backend

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpusnap import PytreeState, RNGState, Snapshot, StateDict

NUM_EPOCHS = 4


def init_model(key):
    k1, k2 = jax.random.split(key)
    return {
        "w": jax.random.normal(k1, (32, 16)) * 0.1,
        "b": jnp.zeros(16),
        "out": jax.random.normal(k2, (16, 1)) * 0.1,
    }


@jax.jit
def loss_fn(params, x, y):
    h = jnp.tanh(x @ params["w"] + params["b"])
    pred = h @ params["out"]
    return jnp.mean((pred - y) ** 2)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--work-dir", default=None)
    parser.add_argument("--resume-from", default=None)
    args = parser.parse_args()
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="tpusnap_example_")

    tx = optax.adam(1e-2)
    params = init_model(jax.random.key(0))
    opt_state = tx.init(params)

    train = PytreeState({"params": params, "opt": opt_state})
    progress = StateDict(epoch=0)
    app_state = {"train": train, "progress": progress, "rng": RNGState()}

    if args.resume_from:
        Snapshot(args.resume_from).restore(app_state)
        print(f"resumed from {args.resume_from} at epoch {progress['epoch']}")

    grad_fn = jax.jit(jax.grad(loss_fn))
    x = np.random.default_rng(0).standard_normal((64, 32)).astype(np.float32)
    y = np.random.default_rng(1).standard_normal((64, 1)).astype(np.float32)

    while progress["epoch"] < NUM_EPOCHS:
        state = train.tree
        grads = grad_fn(state["params"], x, y)
        updates, new_opt = tx.update(grads, state["opt"])
        new_params = optax.apply_updates(state["params"], updates)
        train.tree = {"params": new_params, "opt": new_opt}
        progress["epoch"] += 1

        snap_path = f"{work_dir}/epoch_{progress['epoch']}"
        Snapshot.take(snap_path, app_state)
        loss = float(loss_fn(new_params, x, y))
        print(f"epoch {progress['epoch']}: loss={loss:.5f} snapshot={snap_path}")

    print(f"done. latest snapshot: {work_dir}/epoch_{NUM_EPOCHS}")


if __name__ == "__main__":
    main()
