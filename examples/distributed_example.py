"""Distributed data-parallel checkpointing — the DDP example analog.

Mirrors /root/reference/examples/ddp_example.py:92-96: N processes train
replicas of the same model; ``replicated=["**"]`` declares all state
identical across ranks, so tpusnap writes each value ONCE, with the
write load spread across every rank (partitioner), and every rank can
restore it. Run:

    python examples/distributed_example.py --world-size 2

(The launcher spawns the worker under N jax.distributed CPU processes —
on a real pod slice, run the worker once per host instead.)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_STEPS = 3


def worker(work_dir: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpusnap import PytreeState, Snapshot, StateDict

    rank = jax.process_index()

    # Every rank constructs identical params (in real DDP training they
    # stay identical via gradient all-reduce).
    params = {
        "w": jnp.ones((256, 256)) * 0.01,
        "b": jnp.zeros((256,)),
    }
    progress = StateDict(step=0)
    app_state = {"model": PytreeState(params), "progress": progress}

    path = os.path.join(work_dir, "snap")
    Snapshot.take(path, app_state, replicated=["**"])
    if rank == 0:
        print(f"rank 0: snapshot at {path}")

    # The manifest holds ONE copy of each replicated value.
    snapshot = Snapshot(path)
    manifest = snapshot.get_manifest()
    logical = {p.split("/", 1)[1] for p in manifest}
    assert "model/w" in logical  # PytreeState leaves have named paths
    print(f"rank {rank}: manifest entries {len(manifest)} (deduplicated)")

    # Restore works on every rank.
    target = {"model": PytreeState(jax.tree.map(jnp.zeros_like, params)),
              "progress": StateDict(step=-1)}
    snapshot.restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["model"].tree["w"]), np.asarray(params["w"])
    )
    assert target["progress"]["step"] == 0
    print(f"rank {rank}: restore verified")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--world-size", type=int, default=2)
    parser.add_argument("--work-dir", default=None)
    args = parser.parse_args()

    import tempfile

    from tpusnap.test_utils import run_subprocess_world

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="tpusnap_ddp_")
    outputs = run_subprocess_world(
        worker, world_size=args.world_size, args=[work_dir]
    )
    for rank, out in enumerate(outputs):
        for line in out.strip().splitlines():
            if line.startswith("rank"):
                print(line)


if __name__ == "__main__":
    main()
