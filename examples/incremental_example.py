"""Incremental checkpointing + integrity workflow.

The shape this exists for: a model with a large frozen component (a
pretrained tower / embedding table) and a small trained head. Naive
checkpointing rewrites the frozen gigabytes every step; incremental
snapshots hash them (~19 GB/s) and write only the changed head.

The loop below takes a full snapshot once, then layers incremental
snapshots on it each "epoch", verifies the latest with the integrity
scrub, and finally materializes it (copies the base-referenced blobs in)
so older snapshots can be deleted under a retention policy.

Run: python examples/incremental_example.py [--work-dir DIR]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpusnap.test_utils import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even under a sitecustomize backend

import jax.numpy as jnp
import numpy as np

from tpusnap import PytreeState, Snapshot, StateDict

NUM_EPOCHS = 3


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--work-dir", default=None)
    args = parser.parse_args()
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="tpusnap_inc_example_")

    # Large frozen component + small trained head.
    frozen_tower = np.random.default_rng(0).standard_normal(
        (4096, 512)
    ).astype(np.float32)
    head = jnp.zeros((512, 8), dtype=jnp.float32)

    def snap_path(step: int) -> str:
        return os.path.join(work_dir, f"step_{step}")

    def du(path: str) -> int:
        return sum(
            os.path.getsize(os.path.join(d, f))
            for d, _, fs in os.walk(path)
            for f in fs
        )

    prev = None
    for epoch in range(NUM_EPOCHS):
        head = head + 0.01 * (epoch + 1)  # "training" updates the head only
        app_state = {
            "model": PytreeState({"frozen": frozen_tower, "head": head}),
            "progress": StateDict(epoch=epoch),
        }
        path = snap_path(epoch)
        Snapshot.take(path, app_state, incremental_from=prev)
        kind = "full" if prev is None else f"incremental on {prev}"
        print(f"epoch {epoch}: snapshot {path} ({kind}, {du(path) / 1e6:.1f} MB)")
        if prev is not None:
            # The dedup must actually have happened: an increment holds
            # only the changed head, a small fraction of the full size.
            assert du(path) < du(snap_path(0)) / 10, (du(path), du(snap_path(0)))
        prev = path

    # Verify the latest snapshot end to end (every byte, incl. the blobs
    # it references inside step_0).
    latest = snap_path(NUM_EPOCHS - 1)
    report = Snapshot(latest).verify()
    print(f"verify {latest}: {report.summary()}")
    assert report.clean

    # Retention: keep only the newest snapshot. apply_retention
    # materializes it (copies the base-referenced blobs in, verified)
    # BEFORE deleting the older snapshots it depended on.
    from tpusnap.retention import apply_retention

    plan = apply_retention(work_dir, keep_last=1)
    print(f"retention: {plan.summary()}")
    assert plan.bytes_copied >= frozen_tower.nbytes
    assert os.listdir(work_dir) == [os.path.basename(latest)]

    # The survivor still restores bit-exactly.
    target = {
        "model": PytreeState(
            {
                "frozen": np.zeros_like(frozen_tower),
                "head": jnp.zeros((512, 8), dtype=jnp.float32),
            }
        ),
        "progress": StateDict(epoch=-1),
    }
    Snapshot(latest).restore(target)
    assert target["progress"]["epoch"] == NUM_EPOCHS - 1
    assert np.array_equal(target["model"].tree["frozen"], frozen_tower)
    assert np.array_equal(np.asarray(target["model"].tree["head"]), np.asarray(head))
    assert Snapshot(latest).verify().clean
    print("restore after retention: bit-exact; survivor scrubs clean")


if __name__ == "__main__":
    main()
