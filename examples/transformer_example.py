"""Flagship workload: mesh-sharded transformer training with async
snapshots every epoch and resumable state.

Brings together the whole framework on one model:
- params/optimizer sharded over a ("data", "fsdp", "tensor") mesh
  (dp/fsdp/tp; MoE experts over "data" = ep; optional ring attention
  over "fsdp" = sp/cp),
- ``Snapshot.async_take`` so training resumes while storage I/O drains
  (reference examples + async path, snapshot.py:242-315),
- elastic restore: the snapshot can be restored under a different mesh
  shape (manifest-level resharding).

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_example.py
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpusnap.test_utils import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp
import numpy as np

from tpusnap import PytreeState, Snapshot, StateDict
from tpusnap.models import Transformer, TransformerConfig, make_mesh, make_train_step
from tpusnap.models.transformer import init_train_state

NUM_EPOCHS = 3
STEPS_PER_EPOCH = 4


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--work-dir", default=None)
    parser.add_argument("--resume-from", default=None)
    args = parser.parse_args()
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="tpusnap_xf_")

    mesh = make_mesh()
    use_ring = mesh.shape["fsdp"] > 1
    cfg = TransformerConfig(
        vocab_size=512,
        d_model=128,
        n_heads=8,
        n_layers=2,
        d_ff=256,
        n_experts=4,
        use_ring_attention=use_ring,
    )
    model = Transformer(cfg)
    state = init_train_state(model, mesh, jax.random.PRNGKey(0))
    train_step = make_train_step(model, mesh, learning_rate=1e-2)

    train = PytreeState(state)
    progress = StateDict(epoch=0)
    app_state = {"train": train, "progress": progress}
    if args.resume_from:
        # Background restore: storage reads overlap the train-step
        # compilation below; app_state must not be touched until wait().
        pending_restore = Snapshot(args.resume_from).async_restore(app_state)
    else:
        pending_restore = None

    from jax.sharding import NamedSharding, PartitionSpec as P

    token_sharding = NamedSharding(
        mesh, P("data", "fsdp") if use_ring else P(("data", "fsdp"), None)
    )
    rng = np.random.default_rng(0)
    if pending_restore is not None:
        pending_restore.wait()  # reads overlapped the setup above
        print(f"resumed at epoch {progress['epoch']}")
    pending = None
    while progress["epoch"] < NUM_EPOCHS:
        state = train.tree
        for _ in range(STEPS_PER_EPOCH):
            tokens = jax.device_put(
                jnp.asarray(
                    rng.integers(0, cfg.vocab_size, (4, 32)), dtype=jnp.int32
                ),
                token_sharding,
            )
            state, loss = train_step(state, tokens)
        train.tree = state
        progress["epoch"] += 1

        if pending is not None:
            pending.wait()  # previous epoch's I/O must finish first
        snap_path = f"{work_dir}/epoch_{progress['epoch']}"
        pending = Snapshot.async_take(snap_path, app_state)
        print(
            f"epoch {progress['epoch']}: loss={float(loss):.4f} "
            f"async snapshot -> {snap_path}"
        )

    if pending is None:
        print("nothing to train (resumed at final epoch)")
        return
    snapshot = pending.wait()
    print(f"done; final snapshot committed: {snapshot.path}")


if __name__ == "__main__":
    main()
